"""Mesh-sharded quotient pipeline (ISSUE 19, parallel/sharded_quotient.py).

The contract mirrors the mesh-prove identity (tests/test_parallel.py): the
sharded quotient is the SAME computation as the single-device engine in a
different placement — byte-identical h coefficients across every mesh
shape x NTT mode x NTT kernel combination, with the happy path pinned at
ZERO `quotient_sharded_degraded` ticks and the second identical-shape run
pinned at ZERO compiles (the TC-FRESH-JIT runner caches hold).

Inputs are PRODUCTION inputs: a real prove runs once with the host
quotient hooked (the TestDeviceQuotient idiom), so blinds, grand products
and challenges are the ones a prover would see, and the captured host
h coefficients are the oracle for every combo.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spectre_tpu.fields import bn254 as bn
from spectre_tpu.ops import field_ops as F, ntt as NTT
from spectre_tpu.plonk import quotient_device as QD
from spectre_tpu.utils.health import HEALTH

R = bn.R

needs8 = pytest.mark.skipif(len(jax.devices()) < 8,
                            reason="needs 8 (virtual) devices")
run_slow = pytest.mark.skipif(not os.environ.get("RUN_SLOW"),
                              reason="minutes-scale; set RUN_SLOW=1")


# ---------------------------------------------------------------------------
# production-input capture (one host prove per k, module-cached)
# ---------------------------------------------------------------------------

_CAPTURED: dict = {}


def _capture_quotient_inputs(mk_fixture):
    """Run a CpuBackend prove with `_quotient_host` hooked; return the
    production quotient inputs + the host h-coefficient oracle."""
    import spectre_tpu.plonk.prover as P
    from spectre_tpu.plonk import backend as B
    from spectre_tpu.test_utils import seeded_blinding_rng

    srs, pk, asg = mk_fixture()
    cap = {}
    orig_q = P._quotient_host

    def wrapped(cfg_, dom_, bk_, pk_, polys_, beta, gamma, y):
        h_host = orig_q(cfg_, dom_, bk_, pk_, polys_, beta, gamma, y)

        def fetch(key):
            kind, j = key
            if key in polys_:
                return polys_[key]
            if kind == "shk":
                return pk_.sha_k_poly
            return {"q": pk_.selector_polys, "fix": pk_.fixed_polys,
                    "sig": pk_.sigma_polys, "tab": pk_.table_polys,
                    "shq": pk_.sha_selector_polys}[kind][j]

        cap.update(cfg=cfg_, dom=dom_, fetch=fetch, beta=beta,
                   gamma=gamma, y=y, h_host=h_host)
        return h_host

    P._quotient_host = wrapped
    try:
        P.prove(pk, srs, asg, B.CpuBackend(),
                blinding_rng=seeded_blinding_rng())
    finally:
        P._quotient_host = orig_q
    assert cap, "prove never reached the quotient phase"
    return cap


def _captured_k6():
    """k=6 gate+lookup circuit: n_ext = 256, Bailey 16x16 — divisible by
    every mesh shape in the identity matrix. Captured once per session."""
    if 6 not in _CAPTURED:
        def mk():
            from spectre_tpu.builder import RangeChip
            from spectre_tpu.builder.context import Context
            from spectre_tpu.plonk import backend as B
            from spectre_tpu.plonk.keygen import keygen
            from spectre_tpu.plonk.srs import SRS

            ctx = Context()
            rng = RangeChip(lookup_bits=4)
            g = rng.gate
            a = ctx.load_witness(5)
            b = ctx.load_witness(9)
            c = g.mul(ctx, a, b)
            rng.range_check(ctx, a, 4)
            ctx.expose_public(c)
            cfg = ctx.auto_config(k=6, lookup_bits=4)
            asg = ctx.assignment(cfg)
            srs = SRS.unsafe_setup(8)
            pk = keygen(srs, cfg, asg.fixed, asg.selectors, asg.copies,
                        B.CpuBackend())
            return srs, pk, asg

        _CAPTURED[6] = _capture_quotient_inputs(mk)
    return _CAPTURED[6]


def _captured_k11():
    if 11 not in _CAPTURED:
        from spectre_tpu.test_utils import mesh_prove_fixture
        _CAPTURED[11] = _capture_quotient_inputs(
            lambda: mesh_prove_fixture(k=11))
    return _CAPTURED[11]


def _run_quotient(cap):
    return QD.compute_quotient(cap["cfg"], cap["dom"], cap["fetch"],
                               cap["beta"], cap["gamma"], cap["y"])


# ---------------------------------------------------------------------------
# the identity matrix
# ---------------------------------------------------------------------------

# Tier-1 keeps a representative slice of the shape x mode x kernel matrix
# (the verify budget is shared by the whole suite): every mesh shape on the
# default (radix2, stages) pair, plus both fourstep kernels on the full 8-way
# mesh. The remaining combos run under RUN_SLOW (the matmul kernel is a no-op
# under radix2, and the 1x1/2x1 fourstep arms re-prove what 4x2 proves on a
# smaller permutation group).
_TIER1_COMBOS = [
    ("1x1", "radix2", "stages"),
    ("2x1", "radix2", "stages"),
    ("4x2", "radix2", "stages"),
    ("4x2", "fourstep", "stages"),
    ("4x2", "fourstep", "matmul"),
]
_SLOW_COMBOS = [
    (shape, mode, kernel)
    for shape in ("1x1", "2x1", "4x2")
    for mode in ("radix2", "fourstep")
    for kernel in ("stages", "matmul")
    if (shape, mode, kernel) not in _TIER1_COMBOS
]


@needs8
class TestShardedQuotientIdentity:
    """mesh shape x NTT mode x NTT kernel: byte-identical h coefficients,
    zero degrades. 1x1 is the single-device arm of the identity (the mesh
    gate disengages at one device — that IS the reference path)."""

    @pytest.mark.parametrize("mesh_shape,ntt_mode,ntt_kernel", _TIER1_COMBOS)
    def test_identity_matrix_k6(self, monkeypatch, mesh_shape, ntt_mode,
                                ntt_kernel):
        cap = _captured_k6()
        monkeypatch.setenv("SPECTRE_SHARD_QUOTIENT_MIN_LOGN", "0")
        monkeypatch.setenv("SPECTRE_MESH_SHAPE", mesh_shape)
        monkeypatch.setenv("SPECTRE_NTT_MODE", ntt_mode)
        monkeypatch.setenv("SPECTRE_NTT_KERNEL", ntt_kernel)
        before = HEALTH.get("quotient_sharded_degraded")
        h = _run_quotient(cap)
        assert np.array_equal(h, cap["h_host"]), \
            f"h bytes diverge on {mesh_shape} / {ntt_mode} / {ntt_kernel}"
        assert HEALTH.get("quotient_sharded_degraded") == before, \
            "sharded quotient degraded on an eligible shape"

    @run_slow
    @pytest.mark.parametrize("mesh_shape,ntt_mode,ntt_kernel", _SLOW_COMBOS)
    def test_identity_matrix_k6_full(self, monkeypatch, mesh_shape, ntt_mode,
                                     ntt_kernel):
        self.test_identity_matrix_k6(monkeypatch, mesh_shape, ntt_mode,
                                     ntt_kernel)

    def test_second_identical_run_pins_zero_compiles(self, monkeypatch):
        """The TC-FRESH-JIT contract end-to-end: after one warm pass on a
        shape, a second identical-shape quotient compiles NOTHING — every
        eval/roll/LDE/inverse runner comes out of its plan-keyed cache."""
        from spectre_tpu.observability import compilelog

        cap = _captured_k6()
        monkeypatch.setenv("SPECTRE_SHARD_QUOTIENT_MIN_LOGN", "0")
        monkeypatch.setenv("SPECTRE_MESH_SHAPE", "4x2")
        compilelog.install()
        _run_quotient(cap)                       # warm
        with compilelog.capture() as events:
            h = _run_quotient(cap)
        assert np.array_equal(h, cap["h_host"])
        comp = compilelog.summarize(events)
        assert comp["count"] == 0, \
            f"second identical-shape quotient recompiled: {comp}"


@needs8
@run_slow
class TestShardedQuotientK11:
    """The bench-shape arm (k=11, n_ext = 2^13 — above the default size
    gate, so this also exercises the production gate path untouched)."""

    def test_mesh_byte_identity_k11(self, monkeypatch):
        cap = _captured_k11()
        monkeypatch.setenv("SPECTRE_MESH_SHAPE", "4x2")
        before = HEALTH.get("quotient_sharded_degraded")
        h = _run_quotient(cap)
        assert np.array_equal(h, cap["h_host"])
        assert HEALTH.get("quotient_sharded_degraded") == before


# ---------------------------------------------------------------------------
# dispatch: gates, kill switch, eligibility, visible degrade
# ---------------------------------------------------------------------------

@needs8
class TestShardedDispatch:
    def test_eligibility(self, monkeypatch):
        from spectre_tpu.parallel import sharded_quotient as SQ
        from spectre_tpu.parallel.plan import current_plan

        monkeypatch.setenv("SPECTRE_MESH_SHAPE", "4x2")
        plan = current_plan()
        assert plan.n_devices == 8
        assert SQ.eligible(plan, 256)       # Bailey 16x16: 8 | 16
        assert SQ.eligible(plan, 1 << 13)
        assert not SQ.eligible(plan, 16)    # Bailey 4x4: 8 does not divide
        assert not SQ.eligible(plan, 192)   # not a power of two

    def test_silent_below_gate_and_kill_switch(self, monkeypatch):
        cap = _captured_k6()
        monkeypatch.setenv("SPECTRE_MESH_SHAPE", "4x2")
        before = HEALTH.get("quotient_sharded_degraded")
        # below the size gate (default 18 > logm=8): silently single-device
        monkeypatch.delenv("SPECTRE_SHARD_QUOTIENT_MIN_LOGN", raising=False)
        assert QD._mesh_engine(cap["dom"]) is None
        # kill switch: silently single-device even above the gate
        monkeypatch.setenv("SPECTRE_SHARD_QUOTIENT_MIN_LOGN", "0")
        monkeypatch.setenv("SPECTRE_QUOTIENT_SHARDED", "0")
        assert QD._mesh_engine(cap["dom"]) is None
        assert HEALTH.get("quotient_sharded_degraded") == before

    def test_ineligible_above_gate_degrades_visibly(self, monkeypatch):
        from spectre_tpu.plonk.domain import Domain

        monkeypatch.setenv("SPECTRE_SHARD_QUOTIENT_MIN_LOGN", "0")
        monkeypatch.setenv("SPECTRE_MESH_SHAPE", "4x2")
        before = HEALTH.get("quotient_sharded_degraded")
        # k=2 -> n_ext=16, Bailey 4x4: an 8-way mesh cannot cover it
        assert QD._mesh_engine(Domain(2)) is None
        assert HEALTH.get("quotient_sharded_degraded") == before + 1

    def test_mesh_exception_falls_back_visibly_and_correctly(
            self, monkeypatch):
        """A mesh-path failure mid-quotient must fall back to the local
        engine with the SAME bytes — and tick the degrade counter, never
        silently."""
        from spectre_tpu.parallel import sharded_quotient as SQ

        cap = _captured_k6()
        monkeypatch.setenv("SPECTRE_SHARD_QUOTIENT_MIN_LOGN", "0")
        monkeypatch.setenv("SPECTRE_MESH_SHAPE", "4x2")

        def boom(self, std16):
            raise RuntimeError("injected mesh failure")

        monkeypatch.setattr(SQ.MeshQuotientEngine, "lde", boom)
        before = HEALTH.get("quotient_sharded_degraded")
        h = _run_quotient(cap)
        assert np.array_equal(h, cap["h_host"])
        assert HEALTH.get("quotient_sharded_degraded") == before + 1


# ---------------------------------------------------------------------------
# quotient scalar cache (_TableLRU, ISSUE 19 satellite)
# ---------------------------------------------------------------------------

class TestScalarLRU:
    def test_recompute_after_eviction_is_counted(self):
        from spectre_tpu.ops.msm import _TableLRU

        lru = _TableLRU(4 * 64, label="test scalars")   # four [16] u32 rows
        mk = lambda v: np.full(16, v, np.uint32)
        for v in range(4):
            lru.put(v, None, mk(v))
        assert lru.stats()["evictions"] == 0
        lru.put(4, None, mk(4))                  # evicts the oldest (0)
        assert lru.get(0, None) is None
        lru.put(0, None, mk(0))                  # the rebuild IS a recompute
        st = lru.stats()
        assert st["evictions"] >= 1
        assert st["recomputes"] == 1
        assert st["entries"] == 4

    def test_quotient_exact_under_tiny_budget(self, monkeypatch):
        """Eviction costs recompute time, never correctness: a 2-entry
        scalar budget thrashes (recomputes > 0 in stats) but the h bytes
        stay identical to the uncached-oracle run."""
        from spectre_tpu.ops.msm import _TableLRU

        cap = _captured_k6()
        tiny = _TableLRU(128, label="quotient mont scalar",
                         budget_var="SPECTRE_QUOTIENT_SCALAR_MB")
        monkeypatch.setattr(QD, "_scalar_cache", tiny)
        h = _run_quotient(cap)
        assert np.array_equal(h, cap["h_host"])
        st = tiny.stats()
        assert st["evictions"] > 0
        assert st["recomputes"] > 0, \
            "y re-enters every fold: a 2-entry budget must show recomputes"

    def test_stats_exported(self):
        st = QD.scalar_lru_stats()
        for key in ("hits", "builds", "evictions", "recomputes", "bytes",
                    "budget_bytes", "entries"):
            assert key in st


# ---------------------------------------------------------------------------
# _MATMUL_MAX_LOGN boundary (the cap the sharded inverse legs ride)
# ---------------------------------------------------------------------------

def _poly(n, seed=23):
    return [(i * 2654435761 + seed) % R for i in range(n)]


def _mont(vals):
    return jnp.asarray(F.fr_ctx().encode_np(vals))


class TestMatmulCapBoundary:
    def test_grouped_split_matches_stages(self):
        """The two-level carry split (the mechanism that lifted the cap to
        12) forced onto a small transform: group_width=2 at n=64 runs 16
        groups through per-group carry + group-sum + renormalize, and must
        be byte-identical to the butterfly stages AND to the unsplit
        single-matmul collapse."""
        omega = bn.fr_root_of_unity(6)
        a = _mont(_poly(64, seed=17))
        want = np.asarray(NTT._ntt_stages(a, 6, omega))
        grouped = np.asarray(NTT._ntt_dft_matmul(a, 6, omega, group_width=2))
        unsplit = np.asarray(NTT._ntt_dft_matmul(a, 6, omega))
        assert np.array_equal(want, grouped)
        assert np.array_equal(want, unsplit)

    @pytest.mark.slow
    def test_cap_boundary_full_length(self):
        """n = 2^_MATMUL_MAX_LOGN — the longest transform the exactness
        proof (kernel_lint.lint_matmul_cap) admits — against the stages
        oracle at the REAL production group width."""
        logn = NTT._MATMUL_MAX_LOGN
        assert logn >= 12, "ISSUE 19: the cap must cover n_ext legs to 2^24"
        assert NTT._conv_group_width(logn) < 32, \
            "the boundary length must exercise the grouped path"
        omega = bn.fr_root_of_unity(logn)
        a = _mont(_poly(1 << logn, seed=29))
        got = np.asarray(NTT._ntt_dft_matmul(a, logn, omega))
        want = np.asarray(NTT._ntt_stages(a, logn, omega))
        assert np.array_equal(got, want)

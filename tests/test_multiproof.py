"""Generalized SSZ multiproofs vs the single-branch gadget + random trees."""

import hashlib
import random

from spectre_tpu.gadgets import multiproof as MP
from spectre_tpu.gadgets.ssz_merkle import verify_merkle_proof_native


def _leaves(n, seed=0):
    return [hashlib.sha256(bytes([seed, i])).digest() for i in range(n)]


class TestMultiproof:
    def test_single_leaf_equals_branch_gadget(self):
        """A one-index multiproof must agree with the classic branch path."""
        leaves = _leaves(16)
        tree = MP.merkle_tree(leaves)
        for gidx in (16, 21, 31):
            got_leaves, helpers = MP.create_multiproof(tree, [gidx])
            assert MP.verify_multiproof(tree[1], got_leaves, helpers, [gidx])
            # classic branch: helpers of a single leaf ARE the branch
            # (deepest first), local index = gidx - 16
            assert verify_merkle_proof_native(tree[gidx], helpers, gidx,
                                              tree[1])

    def test_multi_leaf_roundtrip(self):
        random.seed(11)
        leaves = _leaves(32, seed=1)
        tree = MP.merkle_tree(leaves)
        for _ in range(10):
            k = random.randrange(1, 6)
            indices = sorted(random.sample(range(32, 64), k), reverse=True)
            lvs, helpers = MP.create_multiproof(tree, indices)
            assert MP.verify_multiproof(tree[1], lvs, helpers, indices)
        # minimality on a fixture with a shared ancestor: sibling leaves
        # need exactly depth-1 helpers (their subtree root is recomputed)
        sib = [32, 33]
        assert len(MP.get_helper_indices(sib)) == len(
            MP.get_branch_indices(32)) - 1

    def test_mixed_depth_indices(self):
        """Indices at different tree levels (an internal node + a leaf)."""
        leaves = _leaves(16, seed=2)
        tree = MP.merkle_tree(leaves)
        indices = [4, 25]          # level-2 internal node + a leaf
        lvs, helpers = MP.create_multiproof(tree, indices)
        assert MP.verify_multiproof(tree[1], lvs, helpers, indices)

    def test_forgeries_rejected(self):
        leaves = _leaves(16, seed=3)
        tree = MP.merkle_tree(leaves)
        indices = [18, 29]
        lvs, helpers = MP.create_multiproof(tree, indices)
        bad_leaf = [hashlib.sha256(b"x").digest()] + lvs[1:]
        assert not MP.verify_multiproof(tree[1], bad_leaf, helpers, indices)
        bad_help = [hashlib.sha256(b"y").digest()] + helpers[1:]
        assert not MP.verify_multiproof(tree[1], lvs, bad_help, indices)
        assert not MP.verify_multiproof(tree[1], lvs, helpers[:-1], indices)

"""Output-integrity layer (ISSUE 9): verify-before-serve, artifact
scrubber, readiness self-check.

Acceptance pins:
  * an injected `proof.bytes:corrupt` bit-flip on a device prove is
    CAUGHT by self-verify, retried on the CPU backend, and the served
    proof is byte-identical to a clean CPU prove (digest-pinned);
  * with SPECTRE_SELF_VERIFY=off the same fault is served uncaught (the
    negative pin proving the layer is load-bearing) and the
    `prove/self_verify` span never opens;
  * the scrubber quarantines a hand-corrupted result file and removes a
    compaction-orphaned manifest without touching live jobs' artifacts.

Seconds-scale (toy K=6 circuit, CPU JAX) — runs in the default tier and
via `make test-faults`.
"""

import hashlib
import json
import os
import random
import subprocess
import sys
import time

import pytest

from spectre_tpu.utils import faults
from spectre_tpu.utils.health import HEALTH

RUN_SLOW = os.environ.get("RUN_SLOW") == "1"


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# toy prover state: REAL prove + REAL verify on the K=6 circuit
# ---------------------------------------------------------------------------

K = 6


def _toy_proof_setup():
    from spectre_tpu.plonk.constraint_system import Assignment, CircuitConfig
    from spectre_tpu.plonk.keygen import keygen
    from spectre_tpu.plonk.srs import SRS

    cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1, num_fixed=1,
                        lookup_bits=4)
    n = cfg.n
    x_w, y_w = 7, 3
    out = x_w + x_w * y_w
    advice = [[0] * n]
    advice[0][0:5] = [x_w, x_w, y_w, out, 5]
    selectors = [[0] * n]
    selectors[0][0] = 1
    lookup = [[0] * n]
    lookup[0][0] = x_w
    fixed = [[0] * n]
    fixed[0][0] = 5
    copies = [
        ((cfg.col_instance(0), 0), (cfg.col_gate_advice(0), 3)),
        ((cfg.col_fixed(0), 0), (cfg.col_gate_advice(0), 4)),
        ((cfg.col_gate_advice(0), 0), (cfg.col_lookup_advice(0), 0)),
    ]
    srs = SRS.unsafe_setup(K)
    pk = keygen(srs, cfg, fixed, selectors, copies)
    asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
    return pk, srs, asg, out


def _seeded_rng():
    from spectre_tpu.fields import bn254
    rnd = random.Random(0xFA17)
    return lambda: rnd.randrange(bn254.R)


@pytest.fixture(scope="module")
def toy():
    return _toy_proof_setup()


@pytest.fixture(scope="module")
def clean_cpu_proof(toy):
    from spectre_tpu.plonk import backend as B
    from spectre_tpu.plonk.prover import prove
    pk, srs, asg, _ = toy
    return prove(pk, srs, asg, B.get_backend("cpu"),
                 blinding_rng=_seeded_rng())


class _ToyState:
    """ProverState stand-in with real prove/verify on the toy circuit.

    Proving always runs on CPU with seeded blinding (so bytes are
    reproducible); the `backend` kwarg is RECORDED, which is what the
    SDC-retry tests assert on."""

    def __init__(self, toy, jobs=None):
        self.pk, self.srs, self.asg, self.out = toy
        self.jobs = jobs
        self.prove_backends = []      # backend arg per prove call

    def prove_step(self, args, heartbeat=None, backend=None):
        from spectre_tpu.plonk import backend as B
        from spectre_tpu.plonk.prover import prove
        self.prove_backends.append(getattr(backend, "name", None))
        proof = prove(self.pk, self.srs, self.asg, B.get_backend("cpu"),
                      blinding_rng=_seeded_rng())
        return proof, [self.out]

    def verify_proof(self, kind, proof, instances):
        from spectre_tpu.plonk.verifier import verify
        return verify(self.pk.vk, self.srs, [instances], proof)


def _self_verify_count():
    from spectre_tpu.utils import profiling
    return profiling.totals().get("prove/self_verify", {}).get("count", 0)


# ---------------------------------------------------------------------------
# verify-before-serve
# ---------------------------------------------------------------------------

class TestVerifiedProve:
    def test_clean_prove_verifies_and_serves(self, toy, clean_cpu_proof):
        from spectre_tpu.prover_service import selfverify as SV
        st = _ToyState(toy)
        v0 = HEALTH.get("proofs_verified")
        proof, inst = SV.verified_prove(st, "step", None)
        assert proof == clean_cpu_proof
        assert inst == [st.out]
        assert HEALTH.get("proofs_verified") == v0 + 1
        assert st.prove_backends == [None]

    def test_bitflip_caught_cpu_retry_byte_identical(self, toy,
                                                     clean_cpu_proof):
        """THE acceptance pin: an SDC'd device prove is caught, retried
        on CPU, and the served proof is digest-identical to a clean CPU
        prove."""
        from spectre_tpu.prover_service import selfverify as SV
        st = _ToyState(toy)
        faults.install_plan("proof.bytes:corrupt:1")
        v0 = HEALTH.get("proofs_verified")
        f0 = HEALTH.get("proofs_verify_failed")
        r0 = HEALTH.get("proofs_sdc_retried")
        sv0 = _self_verify_count()
        proof, inst = SV.verified_prove(st, "step", None)
        assert hashlib.sha256(proof).digest() \
            == hashlib.sha256(clean_cpu_proof).digest()
        assert proof == clean_cpu_proof
        assert inst == [st.out]
        # two proves: the corrupted one, then the pinned-to-CPU retry
        assert st.prove_backends == [None, "cpu"]
        assert HEALTH.get("proofs_verify_failed") == f0 + 1
        assert HEALTH.get("proofs_sdc_retried") == r0 + 1
        assert HEALTH.get("proofs_verified") == v0 + 1
        assert faults.armed("proof.bytes") == 0
        assert _self_verify_count() == sv0 + 2     # both attempts spanned

    def test_off_serves_fault_uncaught(self, toy, clean_cpu_proof,
                                       monkeypatch):
        """Negative pin: with the knob off the SAME fault reaches the
        caller unverified — proving the layer is load-bearing — and the
        self-verify span never opens."""
        from spectre_tpu.plonk.verifier import verify
        from spectre_tpu.prover_service import selfverify as SV
        monkeypatch.setenv(SV.ENV_VAR, "off")
        st = _ToyState(toy)
        faults.install_plan("proof.bytes:corrupt:1")
        sv0 = _self_verify_count()
        v0 = HEALTH.get("proofs_verified")
        proof, inst = SV.verified_prove(st, "step", None)
        assert proof != clean_cpu_proof                # corrupt bytes SERVED
        assert not verify(st.pk.vk, st.srs, [inst], proof)
        assert st.prove_backends == [None]             # no retry
        assert _self_verify_count() == sv0             # span skipped entirely
        assert HEALTH.get("proofs_verified") == v0

    def test_double_failure_raises_typed(self, toy):
        from spectre_tpu.prover_service import selfverify as SV
        st = _ToyState(toy)
        faults.install_plan("proof.bytes:corrupt:2")   # retry corrupted too
        f0 = HEALTH.get("proofs_verify_failed")
        r0 = HEALTH.get("proofs_sdc_retried")
        with pytest.raises(SV.ProofVerifyFailed, match="self-verification"):
            SV.verified_prove(st, "step", None)
        assert st.prove_backends == [None, "cpu"]
        assert HEALTH.get("proofs_verify_failed") == f0 + 2
        assert HEALTH.get("proofs_sdc_retried") == r0 + 1

    def test_suspect_bytes_quarantined(self, toy, tmp_path):
        """Failed-verify bytes land in results/quarantine/ (named by
        their own sha256) when the state is attached to a store."""
        from spectre_tpu.prover_service import selfverify as SV
        from spectre_tpu.utils.artifacts import ArtifactStore

        class _Jobs:
            store = ArtifactStore(str(tmp_path))

        st = _ToyState(toy, jobs=_Jobs())
        faults.install_plan("proof.bytes:corrupt:2")
        with pytest.raises(SV.ProofVerifyFailed):
            SV.verified_prove(st, "step", None)
        qdir = os.path.join(str(tmp_path), "results", "quarantine")
        names = os.listdir(qdir)
        assert names
        for name in names:            # quarantine names ARE content hashes
            data = open(os.path.join(qdir, name), "rb").read()
            assert name == hashlib.sha256(data).hexdigest() + ".bin"

    def test_sampled_mode_uses_injectable_rng(self, toy, monkeypatch):
        from spectre_tpu.prover_service import selfverify as SV
        monkeypatch.setenv(SV.ENV_VAR, "sampled:0.5")
        draws = iter([0.9, 0.1])       # first skips (0.9 >= p), second checks
        monkeypatch.setattr(SV, "RNG", lambda: next(draws))
        st = _ToyState(toy)
        sv0 = _self_verify_count()
        SV.verified_prove(st, "step", None)
        assert _self_verify_count() == sv0         # 0.9: skipped
        SV.verified_prove(st, "step", None)
        assert _self_verify_count() == sv0 + 1     # 0.1: verified

    def test_policy_parsing_fails_safe(self, monkeypatch):
        from spectre_tpu.prover_service import selfverify as SV
        cases = {"always": ("always", 1.0), "off": ("off", 0.0),
                 "sampled:0.25": ("sampled", 0.25),
                 "sampled:2.0": ("sampled", 1.0),     # clamped
                 "SAMPLED:0.5": ("sampled", 0.5),     # case-insensitive
                 "": ("always", 1.0),
                 "typo": ("always", 1.0),             # fail SAFE, not open
                 "sampled:abc": ("always", 1.0)}
        for raw, want in cases.items():
            monkeypatch.setenv(SV.ENV_VAR, raw)
            assert SV.policy() == want, raw
        monkeypatch.delenv(SV.ENV_VAR)
        assert SV.policy() == ("always", 1.0)

    def test_state_without_verify_proof_skips(self, monkeypatch):
        """Duck-typed fakes (no verify_proof) pass through unverified —
        the RPC plumbing tests keep their canned proofs."""
        from spectre_tpu.prover_service import selfverify as SV

        class _Fake:
            def prove_step(self, args):
                return b"\x01" * 64, [7]

        sv0 = _self_verify_count()
        proof, inst = SV.verified_prove(_Fake(), "step", None)
        assert proof == b"\x01" * 64 and inst == [7]
        assert _self_verify_count() == sv0

    def test_self_check_reruns_after_sdc_retry(self, toy):
        from spectre_tpu.prover_service import selfverify as SV
        st = _ToyState(toy)
        st.self_check = SV.SelfCheck(runner=lambda: True)
        faults.install_plan("proof.bytes:corrupt:1")
        SV.verified_prove(st, "step", None)
        # the box re-proves its readiness after suspected SDC
        assert st.self_check.snapshot() == {"ok": True, "runs": 1,
                                            "last_error": None}


# ---------------------------------------------------------------------------
# readiness self-check
# ---------------------------------------------------------------------------

class TestSelfCheck:
    def test_tiny_circuit_prove_verify_passes(self):
        from spectre_tpu.prover_service import selfverify as SV
        sc = SV.SelfCheck()
        assert sc.run() is True
        assert sc.snapshot() == {"ok": True, "runs": 1, "last_error": None}

    def test_failing_runner_counts_and_records(self):
        from spectre_tpu.prover_service import selfverify as SV
        from spectre_tpu.utils.health import ServiceHealth
        h = ServiceHealth()
        sc = SV.SelfCheck(runner=lambda: False, health=h)
        assert sc.run() is False
        snap = sc.snapshot()
        assert snap["ok"] is False and "failed verification" in snap["last_error"]
        assert h.get("self_check_failures") == 1

        def boom():
            raise RuntimeError("srs missing")

        sc2 = SV.SelfCheck(runner=boom, health=h)
        assert sc2.run() is False
        assert "RuntimeError" in sc2.snapshot()["last_error"]
        assert h.get("self_check_failures") == 2


# ---------------------------------------------------------------------------
# artifact scrubber
# ---------------------------------------------------------------------------

def _digest_runner(method, params):
    faults.check("backend.prove")
    blob = json.dumps([method, params], sort_keys=True).encode()
    return {"proof": "0x" + hashlib.sha256(blob).hexdigest()}


def _mk_queue(tmp_path, **kw):
    from spectre_tpu.prover_service.jobs import JobQueue
    kw.setdefault("concurrency", 1)
    kw.setdefault("scrub_interval", 0)     # periodic thread off: scrub_now
    return JobQueue(_digest_runner, journal_dir=str(tmp_path), **kw)


class TestScrubber:
    def test_corrupt_result_quarantined_live_survives(self, tmp_path):
        """Acceptance pin (scrubber half): a hand-corrupted result file
        is quarantined; the live job's intact artifacts are untouched."""
        q = _mk_queue(tmp_path, scrub_min_age=0)
        j1 = q.submit("m", {"w": 1})
        j2 = q.submit("m", {"w": 2})
        job1, job2 = q.wait(j1, timeout=10), q.wait(j2, timeout=10)
        assert job1.status == "done" and job2.status == "done"
        victim = q.store.path_for(job1.result_digest)
        with open(victim, "r+b") as f:
            f.seek(3)
            f.write(b"\xff")
        c0 = HEALTH.get("artifacts_scrub_corrupt")
        s0 = HEALTH.get("artifacts_scrubbed")
        summary = q.scrub_now()
        assert summary["corrupt"] == 1 and summary["expired"] == 0
        assert summary["scanned"] >= 4      # 2 results + 2 manifests
        assert HEALTH.get("artifacts_scrub_corrupt") == c0 + 1
        assert HEALTH.get("artifacts_scrubbed") == s0 + summary["scanned"]
        assert not os.path.exists(victim)
        assert os.path.exists(os.path.join(
            q.store.quarantine_dir, os.path.basename(victim)))
        # job2's artifacts are untouched and still served
        assert os.path.exists(q.store.path_for(job2.result_digest))
        assert q.result(j2).result == _digest_runner("m", {"w": 2})
        q.stop()

    def test_compact_then_scrub_expires_orphans(self, tmp_path, monkeypatch):
        """Acceptance pin (orphan half), closing the PR-8 follow-up: an
        artifact whose job the journal no longer knows (here: its lines
        hand-pruned, the compaction-retention scenario) is expired by the
        post-compaction scrub pass; live jobs' artifacts survive."""
        q = _mk_queue(tmp_path)
        ja = q.submit("m", {"w": 10})
        jb = q.submit("m", {"w": 11})
        a, b = q.wait(ja, timeout=10), q.wait(jb, timeout=10)
        assert a.result_digest and b.result_digest and b.manifest_digest
        q.stop()
        # drop job B from the journal entirely
        jpath = q.journal.path
        kept = [ln for ln in open(jpath).read().splitlines()
                if json.loads(ln).get("job_id") != jb]
        with open(jpath, "w") as f:
            f.write("\n".join(kept) + "\n")
        e0 = HEALTH.get("artifacts_expired")
        # force startup compaction, then the scrub pass that follows it
        monkeypatch.setenv("SPECTRE_JOURNAL_COMPACT_BYTES", "1")
        q2 = _mk_queue(tmp_path, scrub_min_age=0)
        assert HEALTH.get("artifacts_expired") == e0 + 2   # B's .bin+manifest
        assert not os.path.exists(q2.store.path_for(b.result_digest))
        assert not os.path.exists(q2.store.path_for(
            b.manifest_digest, ".manifest.json"))
        # A survived intact — replayed AND re-readable
        assert os.path.exists(q2.store.path_for(a.result_digest))
        assert q2.result(ja).result == _digest_runner("m", {"w": 10})
        assert q2.manifest(ja) is not None
        q2.stop()

    def test_min_age_guards_unjournaled_writes(self, tmp_path):
        """An orphan younger than scrub_min_age is NOT reaped — the race
        guard for artifacts written moments before their journal record."""
        q = _mk_queue(tmp_path, scrub_min_age=3600)
        orphan = q.store.write(b"freshly written, not yet journaled")
        summary = q.scrub_now()
        assert summary["expired"] == 0
        assert os.path.exists(q.store.path_for(orphan))
        # with the guard off the same file is an expirable orphan
        q.scrubber.min_age_s = 0
        assert q.scrub_now()["expired"] == 1
        assert not os.path.exists(q.store.path_for(orphan))
        q.stop()

    def test_periodic_thread_runs_with_injectable_interval(self, tmp_path):
        q = _mk_queue(tmp_path, scrub_interval=0.01, scrub_min_age=0)
        q.store.write(b"orphan for the periodic pass")
        deadline = time.time() + 5
        while time.time() < deadline:
            if not [n for n in os.listdir(q.store.dir)
                    if n.endswith(".bin")]:
                break
            time.sleep(0.01)
        else:
            pytest.fail("periodic scrubber never expired the orphan")
        q.stop()
        assert q.scrubber._thread is not None

    def test_scrub_skips_foreign_and_tmp_files(self, tmp_path):
        from spectre_tpu.prover_service.scrubber import parse_name
        assert parse_name("ab" * 32 + ".bin") == ("ab" * 32, ".bin")
        assert parse_name("ab" * 32 + ".manifest.json") \
            == ("ab" * 32, ".manifest.json")
        assert parse_name("ab" * 32 + ".bin.tmp") is None
        assert parse_name("ab" * 32) is None            # no suffix
        assert parse_name("notahash.bin") is None
        assert parse_name("ZZ" * 32 + ".bin") is None   # not lowercase hex
        q = _mk_queue(tmp_path, scrub_min_age=0)
        stranger = os.path.join(q.store.dir, "README.txt")
        with open(stranger, "w") as f:
            f.write("operator note")
        summary = q.scrub_now()
        assert summary["skipped"] >= 1
        assert os.path.exists(stranger)                 # never touched
        q.stop()

    def test_cli_scrub_offline(self, tmp_path, capsys):
        from spectre_tpu.prover_service.cli import main
        from spectre_tpu.utils.artifacts import ArtifactStore
        store = ArtifactStore(str(tmp_path))
        store.write(b"orphan: no journal references me")
        main(["scrub", "--params-dir", str(tmp_path)])
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["expired"] == 1 and out["corrupt"] == 0
        assert out["live"] == 0

    def test_compact_then_scrub_keeps_follower_chain_artifacts(
            self, tmp_path, monkeypatch):
        """ISSUE 10 satellite, extending the compact-then-scrub matrix:
        journal compaction + the scrub pass that follows must NEVER
        expire an artifact the follower's update chain references, even
        though no JOB journal record mentions it — the UpdateStore's
        live_artifacts keep-set rides the queue's live-provider hook. A
        genuine orphan in the same pass is still expired."""
        from spectre_tpu.follower.updates import UPDATE_SUFFIX, UpdateStore

        q = _mk_queue(tmp_path)
        jid = q.submit("m", {"w": 20})
        assert q.wait(jid, timeout=10).status == "done"
        store = UpdateStore(str(tmp_path))
        r1 = store.append_committee(1, {"proof": "0x02",
                                        "committee_poseidon": "0xaa"})
        r2 = store.append_committee(2, {"proof": "0x03",
                                        "committee_poseidon": "0xbb"})
        orphan = q.store.write(b"orphan: nothing references me")
        q.stop()

        e0 = HEALTH.get("artifacts_expired")
        # force startup compaction; the post-compaction scrub runs with
        # the follower keep-set registered (the `follow` CLI wiring)
        monkeypatch.setenv("SPECTRE_JOURNAL_COMPACT_BYTES", "1")
        q2 = _mk_queue(tmp_path, scrub_min_age=0,
                       live_providers=[store.live_artifacts])
        assert HEALTH.get("artifacts_expired") == e0 + 1   # the orphan only
        assert not os.path.exists(q2.store.path_for(orphan))
        for rec in (r1, r2):
            assert os.path.exists(
                q2.store.path_for(rec["digest"], UPDATE_SUFFIX))
        # the chain replays and serves from the surviving artifacts
        store2 = UpdateStore(str(tmp_path))
        assert store2.tip_period() == 2
        assert store2.verify_chain()
        assert store2.get_committee(1)["result"]["committee_poseidon"] \
            == "0xaa"
        # negative control: WITHOUT the provider the same artifacts are
        # orphans and the scrub reaps them
        q2.stop()
        q3 = _mk_queue(tmp_path, scrub_min_age=0)
        q3.scrub_now()
        assert not os.path.exists(
            q3.store.path_for(r1["digest"], UPDATE_SUFFIX))
        q3.stop()


class TestScrubberPacing:
    def test_overrun_stretches_interval_and_counts(self, tmp_path):
        """ISSUE 10 satellite: a pass that blew SPECTRE_SCRUB_BUDGET_S
        stretches the next wait by the overrun ratio (capped) and counts
        scrub_passes_deferred; a within-budget pass keeps the cadence."""
        from spectre_tpu.prover_service.scrubber import MAX_STRETCH, Scrubber
        from spectre_tpu.utils.artifacts import ArtifactStore

        ticks = iter([0.0, 120.0,      # pass 1: 120 s wall clock
                      200.0, 205.0,    # pass 2: 5 s
                      300.0, 300.0 + 30.0 * MAX_STRETCH * 4])  # pass 3: huge
        store = ArtifactStore(str(tmp_path))
        sc = Scrubber(store, lambda: set(), min_age_s=0, budget_s=30.0,
                      clock=lambda: next(ticks))
        d0 = HEALTH.get("scrub_passes_deferred")

        sc.scrub()
        assert sc.last_pass_s == 120.0
        assert sc.next_interval(300.0) == pytest.approx(300.0 * 4)  # 120/30
        assert HEALTH.get("scrub_passes_deferred") == d0 + 1

        sc.scrub()                     # fast pass: cadence restored
        assert sc.last_pass_s == 5.0
        assert sc.next_interval(300.0) == 300.0
        assert HEALTH.get("scrub_passes_deferred") == d0 + 1

        sc.scrub()                     # pathological pass: stretch capped
        assert sc.next_interval(300.0) == pytest.approx(300.0 * MAX_STRETCH)
        assert HEALTH.get("scrub_passes_deferred") == d0 + 2

    def test_budget_zero_disables_pacing(self, tmp_path):
        from spectre_tpu.prover_service.scrubber import Scrubber
        from spectre_tpu.utils.artifacts import ArtifactStore
        sc = Scrubber(ArtifactStore(str(tmp_path)), lambda: set(),
                      min_age_s=0, budget_s=0.0)
        sc.last_pass_s = 1e9
        assert sc.next_interval(300.0) == 300.0


# ---------------------------------------------------------------------------
# bench knob (ISSUE 9 small fix)
# ---------------------------------------------------------------------------

class TestBenchSelfVerifyKnob:
    def test_bench_defaults_self_verify_off(self, monkeypatch):
        import bench
        monkeypatch.delenv("SPECTRE_SELF_VERIFY", raising=False)
        monkeypatch.setenv("BENCH_METRIC", "none")   # no benches, just setup
        monkeypatch.setattr(sys, "argv", ["bench.py", "--fast"])
        bench.main()
        assert os.environ.get("SPECTRE_SELF_VERIFY") == "off"

    @pytest.mark.slow
    @pytest.mark.skipif(not RUN_SLOW, reason="bench subprocess (RUN_SLOW=1)")
    def test_bench_fast_clears_floors_with_self_verify_on(self):
        env = dict(os.environ, SPECTRE_SELF_VERIFY="always")
        r = subprocess.run([sys.executable, "bench.py", "--fast"],
                           capture_output=True, text=True, env=env,
                           cwd=os.path.dirname(os.path.dirname(
                               os.path.abspath(__file__))))
        assert r.returncode == 0, r.stdout + "\n" + r.stderr
        recs = [json.loads(ln) for ln in r.stdout.splitlines()
                if ln.startswith("{")]
        assert any(rec.get("self_verify") == "always" for rec in recs)

"""Real-EVM tests: bytecode compiler + metered interpreter.

Reference parity: the reference golden-tests its generated Yul through
revm (`evm_verify`, `prover/src/cli.rs:249-277`). Here the generated
Solidity is compiled to ACTUAL EVM bytecode by `evm/solc.py` and executed
in `evm/vm.py` with mainnet gas metering — deployed size (EIP-170) and gas
become measurements, and the bytecode path cross-checks the line-translate
simulator (two independent executors of the same source)."""

import json
import os

import pytest

from spectre_tpu.evm import encode_calldata, gen_evm_verifier
from spectre_tpu.evm.simulator import run_verifier
from spectre_tpu.evm.solc import Asm, compile_verifier, vm_verify
from spectre_tpu.evm.vm import (deploy, execute, revert_reason,
                                tx_intrinsic_gas)
from spectre_tpu.fields import bn254
from spectre_tpu.plonk.transcript import keccak256

BUILD = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))), "build")


class TestVm:
    def _run(self, build, calldata=b"", gas=10_000_000):
        a = Asm()
        build(a)
        return execute(a.assemble(), calldata, gas)

    def test_arith_and_return(self):
        def prog(a):
            a.push(20)
            a.push(22)
            a.op("ADD")
            a.push(0)
            a.op("MSTORE")
            a.push(32)
            a.push(0)
            a.op("RETURN")
        ok, out, gas = self._run(prog)
        assert ok and int.from_bytes(out, "big") == 42
        # PUSH1 x2 + ADD + PUSH0(2) + MSTORE(3) + mem expansion(3)
        # + PUSH1 + PUSH0 + RETURN(0)
        assert gas == 3 + 3 + 3 + 2 + 3 + 3 + 3 + 2 + 0

    def test_mulmod_matches_python(self):
        R = bn254.R

        def prog(a):
            a.push(R)
            a.push(R - 5)
            a.push(R - 3)
            a.op("MULMOD")
            a.push(0)
            a.op("MSTORE")
            a.push(32)
            a.push(0)
            a.op("RETURN")
        ok, out, _ = self._run(prog)
        assert ok and int.from_bytes(out, "big") == (R - 5) * (R - 3) % R

    def test_keccak_matches_host(self):
        def prog(a):
            a.push(int.from_bytes(b"spectre" + b"\x00" * 25, "big"))
            a.push(0)
            a.op("MSTORE")
            a.push(7)
            a.push(0)
            a.op("SHA3")
            a.push(0)
            a.op("MSTORE")
            a.push(32)
            a.push(0)
            a.op("RETURN")
        ok, out, _ = self._run(prog)
        assert ok and out == keccak256(b"spectre")

    def test_calldata_and_jumps(self):
        # returns calldata word 0 doubled if nonzero else reverts
        def prog(a):
            a.push(0)
            a.op("CALLDATALOAD", "DUP1", "ISZERO")
            a.pushl("fail")
            a.op("JUMPI", "DUP1", "ADD")
            a.push(0)
            a.op("MSTORE")
            a.push(32)
            a.push(0)
            a.op("RETURN")
            a.label("fail")
            a.push(0)
            a.push(0)
            a.op("REVERT")
        ok, out, _ = self._run(prog, (21).to_bytes(32, "big"))
        assert ok and int.from_bytes(out, "big") == 42
        ok2, out2, _ = self._run(prog, b"")
        assert not ok2 and out2 == b""

    def test_invalid_jump_consumes_all_gas(self):
        def prog(a):
            a.push(3)
            a.op("JUMP")
        ok, out, gas = self._run(prog, gas=5000)
        assert not ok and gas == 5000

    def _call_precompile(self, addr, data, ret_size):
        a = Asm()
        for i in range(0, len(data), 32):
            a.push(int.from_bytes(data[i:i + 32].ljust(32, b"\x00"), "big"))
            a.push(i)
            a.op("MSTORE")
        a.push(ret_size)
        a.push(0)
        a.push(len(data))
        a.push(0)
        a.push(addr)
        a.op("GAS", "STATICCALL")
        # return (ok, out): success byte lands at [ret_size]
        a.push(ret_size)
        a.op("MSTORE8")
        a.push(ret_size + 1)
        a.push(0)
        a.op("RETURN")
        ok, out, gas = execute(a.assemble(), b"", 10_000_000)
        assert ok
        return out[ret_size] != 0, out[:ret_size], gas

    def test_ecadd_precompile(self):
        g = bn254.g1_curve
        p = bn254.G1_GEN
        q = g.mul(p, 5)
        data = b"".join(int(v).to_bytes(32, "big")
                        for v in (p[0], p[1], q[0], q[1]))
        ok, out, _ = self._call_precompile(6, data, 64)
        assert ok
        expect = g.mul(p, 6)
        assert int.from_bytes(out[:32], "big") == int(expect[0])
        assert int.from_bytes(out[32:], "big") == int(expect[1])

    def test_ecmul_precompile_and_infinity(self):
        g = bn254.g1_curve
        p = bn254.G1_GEN
        data = (int(p[0]).to_bytes(32, "big") + int(p[1]).to_bytes(32, "big")
                + (7).to_bytes(32, "big"))
        ok, out, _ = self._call_precompile(7, data, 64)
        expect = g.mul(p, 7)
        assert ok and int.from_bytes(out[:32], "big") == int(expect[0])
        # scalar == group order -> infinity encoded as (0, 0)
        data0 = data[:64] + bn254.R.to_bytes(32, "big")
        ok0, out0, _ = self._call_precompile(7, data0, 64)
        assert ok0 and out0 == b"\x00" * 64

    def test_ec_precompile_rejects_off_curve(self):
        data = (1).to_bytes(32, "big") + (1).to_bytes(32, "big") + \
            (7).to_bytes(32, "big")
        ok, _, _ = self._call_precompile(7, data, 64)
        assert not ok

    def test_pairing_precompile(self):
        # e(P, Q) * e(-P, Q) == 1
        from spectre_tpu.plonk.srs import SRS
        srs = SRS.unsafe_setup(4)
        g2 = srs.g2_gen
        p = bn254.G1_GEN
        negp = (p[0], -p[1])

        def enc(g1pt, g2pt):
            return b"".join(int(v).to_bytes(32, "big") for v in (
                g1pt[0], g1pt[1],
                g2pt[0].c[1], g2pt[0].c[0], g2pt[1].c[1], g2pt[1].c[0]))
        ok, out, gas = self._call_precompile(8, enc(p, g2) + enc(negp, g2),
                                             32)
        assert ok and int.from_bytes(out, "big") == 1
        # unbalanced pair -> result 0 (not failure)
        q2 = bn254.g1_curve.mul(p, 2)
        ok2, out2, _ = self._call_precompile(8, enc(p, g2) + enc(q2, g2), 32)
        assert ok2 and int.from_bytes(out2, "big") == 0

    def test_modexp_precompile(self):
        R = bn254.R
        data = ((32).to_bytes(32, "big") * 3
                + (1234567).to_bytes(32, "big")
                + (R - 2).to_bytes(32, "big") + R.to_bytes(32, "big"))
        ok, out, _ = self._call_precompile(5, data, 32)
        assert ok
        assert int.from_bytes(out, "big") == pow(1234567, R - 2, R)

    def test_intrinsic_gas(self):
        assert tx_intrinsic_gas(b"") == 21000
        assert tx_intrinsic_gas(b"\x00\x01") == 21000 + 4 + 16

    def test_deploy_enforces_eip170(self):
        from spectre_tpu.evm.solc import _init_code
        runtime, _ = deploy(_init_code(b"\x00" * 100))
        assert runtime == b"\x00" * 100
        with pytest.raises(Exception):
            deploy(_init_code(b"\x00" * 24577))


@pytest.fixture(scope="module")
def setup():
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_plonk import _tiny_circuit

    from spectre_tpu.plonk.constraint_system import (Assignment,
                                                     CircuitConfig)
    from spectre_tpu.plonk.keygen import keygen
    from spectre_tpu.plonk.prover import prove
    from spectre_tpu.plonk.srs import SRS
    from spectre_tpu.plonk.transcript import KeccakTranscript
    K = 7
    srs = SRS.unsafe_setup(K)
    cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1, num_fixed=1,
                        lookup_bits=4)
    advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
    pk = keygen(srs, cfg, fixed, selectors, copies)
    asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
    proof = prove(pk, srs, asg, transcript=KeccakTranscript())
    src = gen_evm_verifier(pk.vk, srs, num_instances=1)
    return srs, pk, out, proof, src


class TestCompiledVerifier:
    """The generated Solidity compiled to bytecode and run on the VM."""

    def test_compiles_and_accepts_real_proof(self, setup):
        _, _, out, proof, src = setup
        r = vm_verify(src, [out], proof)
        assert r["ok"] and not r["reverted"]
        assert r["gas_execution"] > 45000 + 34000 * 2   # >= pairing floor
        assert r["gas_total"] > r["gas_execution"] + 21000
        assert r["runtime_bytes"] > 1000

    def test_rejects_forgeries_like_the_simulator(self, setup):
        _, _, out, proof, src = setup
        cases = []
        bad = bytearray(proof)
        bad[100] ^= 1
        cases.append(([out], bytes(bad)))          # tampered commitment
        bad2 = bytearray(proof)
        bad2[-100] ^= 1
        cases.append(([out], bytes(bad2)))         # tampered eval
        cases.append(([out + 1], proof))           # wrong public input
        cases.append(([out], proof + b"\x00" * 32))  # wrong length
        for inst, pf in cases:
            r = vm_verify(src, inst, pf)
            sim = run_verifier(src, inst, pf)
            assert r["ok"] is False and sim is False

    def test_revert_reasons_decode(self, setup):
        _, _, out, proof, src = setup
        r = vm_verify(src, [out], proof + b"\x00" * 32)
        assert r["reverted"] and r["revert"] == "proof length"
        bad = bytearray(proof)
        bad[-100] ^= 1
        r2 = vm_verify(src, [out], bytes(bad))
        # a flipped byte near the tail lands in evals or the W commitments:
        # any of these reverts is a correct rejection
        assert r2["reverted"] and r2["revert"] in (
            "identity", "eval range", "ecMul", "ecAdd", "pairing")

    def test_deterministic_bytecode(self, setup):
        src = setup[4]
        rt1, init1, meta1 = compile_verifier(src)
        rt2, init2, _ = compile_verifier(src)
        assert rt1 == rt2 and init1 == init2
        # the deploy wrapper really deploys the runtime
        runtime, _ = deploy(init1) if meta1["eip170_ok"] else (rt1, 0)
        assert runtime == rt1

    def test_gas_against_static_model(self, setup):
        """The static estimator (gas.py) should be within 2x of metered
        reality — it exists to be a sanity bound, not an oracle."""
        from spectre_tpu.evm import estimate_gas
        _, _, out, proof, src = setup
        cd = encode_calldata([out], proof)
        est = estimate_gas(src, calldata=cd)["gas_total"]
        real = vm_verify(src, [out], proof)["gas_total"]
        assert real / 2 < est < real * 2, (est, real)


class TestAccumulatorBytecode:
    """num_acc_limbs=12 deferred-pairing path through the real EVM."""

    def test_accumulator_paths(self, setup):
        import sys
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from test_evm import TestAccumulatorPairing
        srs = setup[0]
        src, inst, proof = TestAccumulatorPairing._acc_proof(
            srs, 12345, valid=True)
        r = vm_verify(src, inst, proof)
        assert r["ok"]
        src2, inst2, proof2 = TestAccumulatorPairing._acc_proof(
            srs, 12345, valid=False)
        r2 = vm_verify(src2, inst2, proof2)
        # outer PLONK proof is valid; only the deferred pairing fails,
        # which returns false rather than reverting
        assert r2["ok"] is False and not r2["reverted"]


class TestFlagshipBytecode:
    """The checked-in Testnet-512 aggregation verifier, compiled for real:
    deployed size vs EIP-170 and metered gas replace the static estimates
    (VERDICT r4 'unknowable without a compiler' item)."""

    def test_flagship_real_measurements(self):
        sol = os.path.join(BUILD,
                           "aggregation_sync_step_testnet_21_verifier.sol")
        pf = os.path.join(BUILD, "agg_step_testnet_21_keccak.proof")
        if not (os.path.exists(sol) and os.path.exists(pf)):
            pytest.skip("flagship artifacts not in build/")
        with open(sol) as f:
            src = f.read()
        with open(pf, "rb") as f:
            proof = f.read()
        with open(pf + ".instances.json") as f:
            inst = [int(v, 16) for v in json.load(f)["instances"]]
        r = vm_verify(src, inst, proof)
        assert r["ok"], r
        # the real numbers, asserted loosely so the test documents them
        assert 500_000 < r["gas_total"] < 3_000_000
        assert r["runtime_bytes"] > 24576 * 0.5
        bad = bytearray(proof)
        bad[41] ^= 1
        assert not vm_verify(src, inst, bytes(bad))["ok"]


def test_revert_reason_decoder():
    payload = (bytes.fromhex("08c379a0")
               + (32).to_bytes(32, "big") + (5).to_bytes(32, "big")
               + b"hello".ljust(32, b"\x00"))
    assert revert_reason(payload) == "hello"
    assert revert_reason(b"") is None

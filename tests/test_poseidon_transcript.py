"""Poseidon (algebraic) Fiat–Shamir transcript + its in-circuit mirror.

Reference parity: snark-verifier's `PoseidonTranscript` pair —
`NativeLoader` (host challenge derivation for aggregation-bound snarks) and
`Rc<Halo2Loader>` (the same derivation as constraints inside the
aggregation circuit). The native/chip parity test is the load-bearing one:
the aggregation circuit is sound only if the in-circuit challenges equal the
host verifier's.
"""

import random

from spectre_tpu.builder.context import Context
from spectre_tpu.builder.range_chip import RangeChip
from spectre_tpu.builder.transcript_chip import TranscriptChip
from spectre_tpu.fields import bn254
from spectre_tpu.plonk.keygen import keygen
from spectre_tpu.plonk.mock import mock_prove
from spectre_tpu.plonk.prover import prove
from spectre_tpu.plonk.srs import SRS
from spectre_tpu.plonk.transcript import (PoseidonTranscript,
                                          point_to_transcript_elements)
from spectre_tpu.plonk.verifier import verify


class TestPoseidonTranscript:
    def test_prove_verify_roundtrip(self):
        random.seed(3)
        ctx = Context()
        rng = RangeChip(lookup_bits=8)
        g = rng.gate
        a = ctx.load_witness(1234)
        b = ctx.load_witness(5678)
        c = g.mul(ctx, a, b)
        rng.range_check(ctx, a, 16)
        ctx.expose_public(c)
        cfg = ctx.auto_config(k=10, lookup_bits=8)
        asg = ctx.assignment(cfg)
        srs = SRS.unsafe_setup(10)
        pk = keygen(srs, cfg, asg.fixed, asg.selectors, asg.copies)
        proof = prove(pk, srs, asg, transcript=PoseidonTranscript())
        assert verify(pk.vk, srs, asg.instances, proof,
                      transcript_cls=PoseidonTranscript)
        # challenges differ from the byte transcripts: cross-verify must fail
        try:
            ok = verify(pk.vk, srs, asg.instances, proof)
        except AssertionError:
            ok = False
        assert not ok
        # tamper
        bad = bytearray(proof)
        bad[33] ^= 1
        try:
            ok = verify(pk.vk, srs, asg.instances, bytes(bad),
                        transcript_cls=PoseidonTranscript)
        except AssertionError:
            ok = False
        assert not ok

    def test_point_encoding_limbs(self):
        els = point_to_transcript_elements(bn254.G1_GEN)
        assert len(els) == 6
        x = sum(v << (88 * i) for i, v in enumerate(els[:3]))
        y = sum(v << (88 * i) for i, v in enumerate(els[3:]))
        assert (x, y) == (int(bn254.G1_GEN[0]), int(bn254.G1_GEN[1]))


class TestTranscriptChip:
    def test_mirrors_native_challenges(self):
        random.seed(5)
        g1 = bn254.g1_curve
        pts, p = [], bn254.G1_GEN
        for _ in range(3):
            p = g1.double(p)
            pts.append(p)
        scalars = [random.randrange(bn254.R) for _ in range(5)]

        nt = PoseidonTranscript()
        nt._absorb_bytes(b"\x01" * 32)
        for s in scalars[:2]:
            nt.common_scalar(s)
        c1 = nt.challenge()
        for q in pts:
            nt.common_point(q)
        c2 = nt.challenge()
        c3 = nt.challenge()  # empty-pending squeeze

        ctx = Context()
        tc = TranscriptChip()
        tc.absorb_constant_bytes(ctx, b"\x01" * 32)
        tc.absorb([ctx.load_witness(s) for s in scalars[:2]])
        d1 = tc.challenge(ctx)
        for q in pts:
            tc.absorb([ctx.load_witness(v)
                       for v in point_to_transcript_elements(q)])
        d2 = tc.challenge(ctx)
        d3 = tc.challenge(ctx)
        assert (c1, c2, c3) == (d1.value, d2.value, d3.value)
        cfg = ctx.auto_config(k=12, lookup_bits=8)
        assert mock_prove(cfg, ctx.assignment(cfg))

"""Wide SHA-256 region: chip digest parity, mock satisfaction, soundness
probes (forged digest / zeroed act rejected), and a real prove/verify."""

import hashlib

import numpy as np
import pytest

from spectre_tpu.builder import Context, GateChip
from spectre_tpu.builder.sha256_wide_chip import Sha256WideChip
from spectre_tpu.gadgets import ssz_merkle as M
from spectre_tpu.plonk.constraint_system import (SHA_ACT_WORD, SHA_OUT_ROW,
                                                 SHA_SEED_ROW, SHA_SLOT_ROWS)
from spectre_tpu.plonk.mock import mock_prove


def _build_digest(msg: bytes):
    ctx = Context()
    sha = Sha256WideChip(GateChip())
    cells = M.load_bytes_checked(ctx, sha, msg)
    digest = sha.digest_bytes(ctx, cells)
    got = b"".join(int(w.value).to_bytes(4, "big") for w in digest)
    return ctx, sha, digest, got


class TestWideDigest:
    def test_digest_matches_hashlib(self):
        for msg in (b"", b"abc", b"x" * 48, b"y" * 64, b"z" * 100):
            _, _, _, got = _build_digest(msg)
            assert got == hashlib.sha256(msg).digest(), msg

    def test_two_to_one_matches_native(self):
        ctx = Context()
        sha = Sha256WideChip(GateChip())
        left = M.bytes_to_chunk(ctx, sha, M.load_bytes_checked(ctx, sha, b"L" * 32))
        right = M.bytes_to_chunk(ctx, sha, M.load_bytes_checked(ctx, sha, b"R" * 32))
        node = sha.digest_two_to_one(ctx, left, right)
        got = b"".join(int(w.value).to_bytes(4, "big") for w in node)
        assert got == M.sha256_pair_native(b"L" * 32, b"R" * 32)

    def test_mock_satisfied(self):
        ctx, _, digest, _ = _build_digest(b"spectre wide sha")
        for w in digest:
            ctx.expose_public(w.cell)
        cfg = ctx.auto_config(k=9, lookup_bits=5)
        assert cfg.num_sha_slots == 1
        assert mock_prove(cfg, ctx.assignment(cfg))

    def test_merkleize_with_wide_chip(self):
        ctx = Context()
        sha = Sha256WideChip(GateChip())
        chunks = [M.bytes_to_chunk(ctx, sha,
                                   M.load_bytes_checked(ctx, sha, bytes([i]) * 32))
                  for i in range(3)]
        root = M.merkleize_chunks(ctx, sha, chunks, limit=4)
        got = b"".join(int(w.value).to_bytes(4, "big") for w in root)
        want = M.merkleize_chunks_native([bytes([i]) * 32 for i in range(3)],
                                         limit=4)
        assert got == want
        cfg = ctx.auto_config(k=10, lookup_bits=5)
        assert mock_prove(cfg, ctx.assignment(cfg))


class TestWideSoundness:
    def test_forged_digest_bit_rejected(self):
        """Flip one ladder bit in the region witness: an identity must fail."""
        ctx, _, _, _ = _build_digest(b"forge me")
        cfg = ctx.auto_config(k=9, lookup_bits=5)
        asg = ctx.assignment(cfg)
        # flip an a-ladder bit in round 30 of slot 0
        row = 4 + 30
        asg.sha_bit[32 + 7, row] ^= 1
        with pytest.raises(AssertionError):
            mock_prove(cfg, asg)

    def test_forged_output_word_rejected(self):
        """Tamper the h_out word (and its mirrored main cell consistently):
        the out-row identity must fail."""
        ctx, _, digest, _ = _build_digest(b"forge me 2")
        cfg = ctx.auto_config(k=9, lookup_bits=5)
        asg = ctx.assignment(cfg)
        nsl = len(ctx.sha_slots)
        orow = (nsl - 1) * SHA_SLOT_ROWS + SHA_OUT_ROW
        asg.sha_word[0, orow] ^= 1
        # rejected either by the mirror-copy check or by the out identity
        with pytest.raises(AssertionError):
            mock_prove(cfg, asg)

    def test_carry_shift_digest_forgery_rejected(self):
        """The ±2^32 digest forgery (review PoC): flip the out-row carry bit
        AND consistently shift the h_out word, its mirrored advice cell and
        the instance by 2^32. Must be rejected by the 32-bit range check on
        the mirror (pre-fix, mock_prove ACCEPTED this)."""
        ctx, _, digest, _ = _build_digest(b"carry forge")
        for w in digest:
            ctx.expose_public(w.cell)
        cfg = ctx.auto_config(k=9, lookup_bits=5)
        asg = ctx.assignment(cfg)
        nsl = len(ctx.sha_slots)
        orow = (nsl - 1) * SHA_SLOT_ROWS + SHA_OUT_ROW
        from spectre_tpu.plonk.constraint_system import SHA_CARRY
        # find a word whose true out-carry is 1 (so flipping to 0 shifts +2^32)
        target = None
        for j in range(8):
            if int(asg.sha_bit[SHA_CARRY + j, orow]) == 1:
                target = j
                break
        if target is None:
            pytest.skip("no carry-1 word in this digest (vanishing odds)")
        asg.sha_bit[SHA_CARRY + target, orow] = 0
        forged = int(asg.sha_word[target, orow]) + (1 << 32)
        asg.sha_word[target, orow] = forged
        # shift the mirrored advice cell + every stream copy of it
        mirror_idx = digest[target].cell.index
        old = digest[target].cell.value
        for c in range(cfg.num_advice):
            col = asg.advice[c]
            for r in range(len(col)):
                if col[r] == old:
                    col[r] = forged
        asg.instances[0][target] = forged
        with pytest.raises(AssertionError):
            mock_prove(cfg, asg)

    def test_zeroed_act_rejected(self):
        """Zeroing act (the K-less hash attack) must violate either the act
        pin copy or the round identity."""
        ctx, _, _, _ = _build_digest(b"act attack")
        cfg = ctx.auto_config(k=9, lookup_bits=5)
        asg = ctx.assignment(cfg)
        asg.sha_word[SHA_ACT_WORD, :SHA_OUT_ROW + 1] = 0
        with pytest.raises(AssertionError):
            mock_prove(cfg, asg)


class TestWideProve:
    def test_prove_verify_roundtrip(self):
        from spectre_tpu.plonk.keygen import keygen
        from spectre_tpu.plonk.prover import prove
        from spectre_tpu.plonk.srs import SRS
        from spectre_tpu.plonk.verifier import verify

        ctx, _, digest, _ = _build_digest(b"prove the wide region")
        for w in digest[:2]:
            ctx.expose_public(w.cell)
        cfg = ctx.auto_config(k=9, lookup_bits=5)
        advice, lookup, fixed, selectors, copies, instances, _bp = \
            ctx.layout(cfg)
        srs = SRS.unsafe_setup(11)
        pk = keygen(srs, cfg, fixed, selectors, copies)
        asg = ctx.assignment(cfg)
        proof = prove(pk, srs, asg)
        assert verify(pk.vk, srs, instances, proof)
        bad = [list(instances[0])]
        bad[0][0] ^= 1
        assert not verify(pk.vk, srs, bad, proof)

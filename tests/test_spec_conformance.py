"""Consensus-spec-test conformance: the official pyspec light_client/sync
fixture format drives both circuits' witnesses.

Reference parity: `lightclient-circuits/tests/step.rs:29-117` walks
`consensus-spec-tests/tests/minimal/capella/light_client/sync/pyspec_tests/*`
through `test-utils::read_test_files_and_gen_witness` and asserts both
circuits are satisfied plus the Poseidon-instance cross-check
(`tests/step.rs:113-116`). The vendored fixture here is self-generated in
the EXACT official file layout (`bootstrap.ssz_snappy` + `steps.yaml` +
`updates_*.ssz_snappy`, snappy raw-block over SSZ), so real downloaded
fixtures drop in unchanged.
"""

import glob
import os
import sys
import unittest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spectre_tpu.fields import bls12_381 as bls
from spectre_tpu.models import CommitteeUpdateCircuit, StepCircuit
from spectre_tpu.preprocessor import snappy_codec, spec_tests, ssz
from spectre_tpu.spec import MINIMAL

SPEC_TEST_GLOB = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "consensus-spec-tests", "tests", "minimal", "capella", "light_client",
    "sync", "pyspec_tests", "*")

RUN_SLOW = os.environ.get("RUN_SLOW") == "1"


def spec_test_dirs():
    return sorted(d for d in glob.glob(SPEC_TEST_GLOB) if os.path.isdir(d))


def provable_test_dirs():
    """Fixtures Spectre can prove: those opening with process_update steps
    (reference cuts at the first force_update, `test-utils/src/lib.rs:64-66`)
    whose first update carries finality (Spectre proves only finalized
    updates; the official no-finality shape is covered separately)."""
    out = []
    for d in spec_test_dirs():
        try:
            step_args, _ = spec_tests.read_test_files_and_gen_witness(d, MINIMAL)
        except ValueError:
            continue
        if spec_tests.update_has_finality(step_args):
            out.append(d)
    return out


class TestSnappyCodec(unittest.TestCase):
    def test_roundtrip(self):
        for payload in (b"", b"a", b"hello" * 1000, os.urandom(70000)):
            self.assertEqual(
                snappy_codec.decompress(snappy_codec.compress(payload)),
                payload)

    def test_copy_elements(self):
        # hand-built stream with a 2-byte-offset copy: "abcdabcd"
        # literal "abcd" (tag len-1=3 -> 0b0000_1100), copy2 len=4 off=4
        stream = bytes([8]) + bytes([3 << 2]) + b"abcd" + \
            bytes([((4 - 1) << 2) | 2]) + (4).to_bytes(2, "little")
        self.assertEqual(snappy_codec.decompress(stream), b"abcdabcd")

    def test_overlapping_copy(self):
        # literal "ab" + copy len=6 off=2 -> "ab" * 4 (RLE-style overlap)
        stream = bytes([8]) + bytes([1 << 2]) + b"ab" + \
            bytes([((6 - 1) << 2) | 2]) + (2).to_bytes(2, "little")
        self.assertEqual(snappy_codec.decompress(stream), b"abababab")


class TestSSZCodec(unittest.TestCase):
    def test_beacon_header_root_matches_witness_types(self):
        from spectre_tpu.witness.types import BeaconBlockHeader
        h = ssz.Obj(slot=7, proposer_index=3, parent_root=b"\x01" * 32,
                    state_root=b"\x02" * 32, body_root=b"\x03" * 32)
        wt = BeaconBlockHeader(slot=7, proposer_index=3,
                               parent_root=b"\x01" * 32,
                               state_root=b"\x02" * 32, body_root=b"\x03" * 32)
        self.assertEqual(ssz.BEACON_BLOCK_HEADER.hash_tree_root(h),
                         wt.hash_tree_root())
        enc = ssz.BEACON_BLOCK_HEADER.encode(h)
        self.assertEqual(len(enc), 112)
        self.assertEqual(ssz.BEACON_BLOCK_HEADER.decode(enc), h)

    def test_variable_container_roundtrip(self):
        t = ssz.execution_payload_header(256, 32)
        v = ssz.Obj(parent_hash=b"\x01" * 32, fee_recipient=b"\x02" * 20,
                    state_root=b"\x03" * 32, receipts_root=b"\x04" * 32,
                    logs_bloom=b"\x00" * 256, prev_randao=b"\x05" * 32,
                    block_number=9, gas_limit=10, gas_used=11, timestamp=12,
                    extra_data=b"xyz", base_fee_per_gas=1 << 100,
                    block_hash=b"\x06" * 32, transactions_root=b"\x07" * 32,
                    withdrawals_root=b"\x08" * 32)
        self.assertEqual(t.decode(t.encode(v)), v)

    def test_bitvector_padding_rejected(self):
        bv = ssz.Bitvector(4)
        self.assertEqual(bv.decode(bv.encode([1, 0, 1, 0])), [1, 0, 1, 0])
        with self.assertRaises(AssertionError):
            bv.decode(b"\xff")  # bits 4..7 set


def _dir(name: str) -> str:
    return os.path.join(os.path.dirname(SPEC_TEST_GLOB), name)


class TestLoaderCaseShapes(unittest.TestCase):
    """The official suite's non-happy-path shapes, each exercising a loader
    branch (`test-utils/src/lib.rs:64-85` semantics)."""

    def test_force_update_cut(self):
        """Steps = [process_update, force_update]: the valid-updates cut
        keeps exactly the leading process_update."""
        d = _dir("force_update_cut_selfgen")
        if not os.path.isdir(d):
            self.skipTest("fixture not vendored")
        from spectre_tpu.test_utils import read_spec_test_steps
        kinds = [k for k, _ in read_spec_test_steps(d)]
        self.assertEqual(kinds, ["process_update", "force_update"])
        updates = spec_tests.valid_updates_from_test_path(d, MINIMAL)
        self.assertEqual(len(updates), 1)

    def test_multi_update_sequence(self):
        """Two sequential process_update steps load IN ORDER."""
        d = _dir("multi_update_selfgen")
        if not os.path.isdir(d):
            self.skipTest("fixture not vendored")
        updates = spec_tests.valid_updates_from_test_path(d, MINIMAL)
        self.assertEqual(len(updates), 2)
        self.assertLess(updates[0].attested_header.beacon.slot,
                        updates[1].attested_header.beacon.slot)
        # each update independently converts to a verifiable witness
        bootstrap = spec_tests.load_snappy_ssz(
            os.path.join(d, "bootstrap.ssz_snappy"),
            ssz.light_client_bootstrap(MINIMAL))
        meta = spec_tests.read_meta(d)
        gvr = bytes.fromhex(meta["genesis_validators_root"].replace("0x", ""))
        for u in updates:
            args = spec_tests.to_sync_circuit_witness(
                MINIMAL, bootstrap.current_sync_committee, u, gvr)
            pts = [(bls.Fq(x), bls.Fq(y)) for (x, y), b in
                   zip(args.pubkeys_uncompressed, args.participation_bits) if b]
            sig = bls.g2_decompress(args.signature_compressed)
            self.assertTrue(bls.fast_aggregate_verify(
                pts, args.signing_root(), sig, dst=MINIMAL.dst))

    def test_force_update_opener_not_provable(self):
        """A fixture OPENING with force_update (skipped-period shape) has no
        provable prefix: the loader must raise, not mis-prove."""
        d = _dir("skipped_period_force_update_selfgen")
        if not os.path.isdir(d):
            self.skipTest("fixture not vendored")
        self.assertEqual(
            spec_tests.valid_updates_from_test_path(d, MINIMAL), [])
        with self.assertRaises(ValueError):
            spec_tests.read_test_files_and_gen_witness(d, MINIMAL)

    def test_no_finality_update_rejected_by_preverification(self):
        """The official no-finality update shape converts to a witness whose
        zeroed finality branch must FAIL native pre-verification (Spectre
        proves only finalized updates)."""
        d = _dir("process_update_no_finality_selfgen")
        if not os.path.isdir(d):
            self.skipTest("fixture not vendored")
        step_args, rot_args = \
            spec_tests.read_test_files_and_gen_witness(d, MINIMAL)
        self.assertFalse(spec_tests.update_has_finality(step_args))
        with self.assertRaises(AssertionError):
            spec_tests.verify_witness_branches(MINIMAL, step_args, rot_args)
        # ... but the signature over the attested header is still real
        pts = [(bls.Fq(x), bls.Fq(y)) for (x, y), b in
               zip(step_args.pubkeys_uncompressed,
                   step_args.participation_bits) if b]
        sig = bls.g2_decompress(step_args.signature_compressed)
        self.assertTrue(bls.fast_aggregate_verify(
            pts, step_args.signing_root(), sig, dst=MINIMAL.dst))


class TestSpecConformance(unittest.TestCase):
    """The loader is live: every vendored/downloaded fixture dir is walked."""

    def test_fixture_dirs_exist(self):
        self.assertTrue(spec_test_dirs(),
                        "no consensus-spec-tests fixtures vendored")

    def test_all_case_shapes_vendored(self):
        names = {os.path.basename(d) for d in spec_test_dirs()}
        for want in ("light_client_sync_selfgen", "multi_update_selfgen",
                     "force_update_cut_selfgen",
                     "process_update_no_finality_selfgen",
                     "skipped_period_force_update_selfgen"):
            self.assertIn(want, names)

    def test_witness_generation_and_native_checks(self):
        for d in provable_test_dirs():
            with self.subTest(fixture=os.path.basename(d)):
                step_args, rot_args = \
                    spec_tests.read_test_files_and_gen_witness(d, MINIMAL)
                n = MINIMAL.sync_committee_size
                self.assertEqual(len(step_args.pubkeys_uncompressed), n)
                self.assertEqual(len(rot_args.pubkeys_compressed), n)
                # every merkle branch verifies natively (preprocessor parity)
                spec_tests.verify_witness_branches(MINIMAL, step_args, rot_args)
                # the BLS aggregate signature verifies natively
                pts = [(bls.Fq(x), bls.Fq(y)) for (x, y), b in
                       zip(step_args.pubkeys_uncompressed,
                           step_args.participation_bits) if b]
                sig = bls.g2_decompress(step_args.signature_compressed)
                self.assertTrue(bls.fast_aggregate_verify(
                    pts, step_args.signing_root(), sig, dst=MINIMAL.dst))
                # instance computation runs (poseidon + pub-input commitment)
                si = StepCircuit.get_instances(step_args, MINIMAL)
                self.assertEqual(len(si), 2)
                ci = CommitteeUpdateCircuit.get_instances(rot_args, MINIMAL)
                self.assertTrue(ci)

    def test_initial_poseidon_matches_step_instance(self):
        """Contract-bootstrap poseidon == step circuit's poseidon instance
        (both hash the bootstrap/current committee)."""
        for d in provable_test_dirs():
            with self.subTest(fixture=os.path.basename(d)):
                step_args, _ = \
                    spec_tests.read_test_files_and_gen_witness(d, MINIMAL)
                _, poseidon = spec_tests.get_initial_sync_committee_poseidon(
                    d, MINIMAL)
                self.assertEqual(poseidon,
                                 StepCircuit.get_instances(step_args, MINIMAL)[1])

    def test_steps_yaml_checks_match_headers(self):
        from spectre_tpu.test_utils import read_spec_test_steps
        for d in provable_test_dirs():
            steps = read_spec_test_steps(d)
            step_args, _ = spec_tests.read_test_files_and_gen_witness(d, MINIMAL)
            kinds = [k for k, _ in steps]
            self.assertEqual(kinds[0], "process_update")
            checks = steps[0][1]["checks"]
            self.assertEqual(
                checks["finalized_header"]["beacon_root"],
                "0x" + step_args.finalized_header.hash_tree_root().hex())
            self.assertEqual(
                checks["optimistic_header"]["beacon_root"],
                "0x" + step_args.attested_header.hash_tree_root().hex())

    @unittest.skipUnless(RUN_SLOW, "Minimal-preset mocks are multi-minute "
                                   "(set RUN_SLOW=1)")
    def test_eth2_spec_mock(self):
        """Reference CI's `test_eth2_spec_mock_1`: mock-prove both circuits
        from the spec-test witness at the Minimal preset."""
        d = provable_test_dirs()[0]
        step_args, rot_args = \
            spec_tests.read_test_files_and_gen_witness(d, MINIMAL)
        self.assertTrue(CommitteeUpdateCircuit.mock(rot_args, MINIMAL, k=18))
        self.assertTrue(StepCircuit.mock(step_args, MINIMAL, k=19))


if __name__ == "__main__":
    unittest.main()

"""Pallas MSM kernel math: the in-kernel field/EC functions are pure jnp on
limb-row lists, so they are testable WITHOUT pallas_call (Mosaic needs real
TPU). Everything goes through jit — eager execution of the ~30k-op unrolled
kernels costs minutes per call. ONE small-shape test runs the actual
pallas_call in interpret mode (seconds-scale compile, the off-TPU dispatch
SPECTRE_MSM_IMPL=pallas rides) — see TestInterpretMode.

Oracle: ops/ec (already property-tested against the host curve). The full
SoA MSM parity run is RUN_SLOW (several compile shapes); device execution of
the actual pallas_call happens via bench.py on TPU."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from spectre_tpu.fields import bn254 as bn
from spectre_tpu.ops import ec, field_ops as F
from spectre_tpu.ops import msm_pallas as MP


def _pts(n, seed=3):
    g = bn.g1_curve
    return [g.mul(bn.G1_GEN, seed * k + 1) for k in range(n)]


_jit_padd = jax.jit(MP._k_padd)
_jit_mont_mul = jax.jit(MP._k_mont_mul)
_jit_add = jax.jit(MP._k_add)
_jit_sub = jax.jit(MP._k_sub)


@pytest.fixture(scope="module")
def batch():
    n = 8
    aos = ec.encode_points(_pts(2 * n))
    return aos[:n], aos[n:]


class TestLayout:
    def test_soa_roundtrip(self, batch):
        a, _ = batch
        back = MP.from_soa(MP.to_soa(a))
        assert np.array_equal(np.asarray(back), np.asarray(a))

    def test_inf_soa_matches_ec(self):
        want = np.asarray(ec.inf_point((4,)))
        got = np.asarray(MP.from_soa(MP.inf_soa(4)))
        assert np.array_equal(got, want)


class TestKernelMath:
    """_k_* functions on jnp rows vs the tested AoS ops."""

    def test_mont_mul(self, batch):
        a, b = batch
        ctx = F.fq_ctx()
        got = _jit_mont_mul(MP.to_soa(a)[:MP.NL], MP.to_soa(b)[:MP.NL])
        want = np.asarray(jnp.transpose(
            F.mont_mul(ctx, a[:, 0], b[:, 0]), (1, 0)))
        assert np.array_equal(np.asarray(got), want)

    def test_add_sub(self, batch):
        a, b = batch
        ctx = F.fq_ctx()
        x, y = MP.to_soa(a)[:MP.NL], MP.to_soa(b)[:MP.NL]
        want_add = np.asarray(jnp.transpose(F.add(ctx, a[:, 0], b[:, 0]), (1, 0)))
        want_sub = np.asarray(jnp.transpose(F.sub(ctx, a[:, 0], b[:, 0]), (1, 0)))
        assert np.array_equal(np.asarray(_jit_add(x, y)), want_add)
        assert np.array_equal(np.asarray(_jit_sub(x, y)), want_sub)

    def test_sub_zero_normalizes(self, batch):
        """p - 0 must normalize to 0-lane behavior (cond-sub path): a - 0 == a."""
        a, _ = batch
        x = MP.to_soa(a)[:MP.NL]
        zero = jnp.zeros_like(x)
        got = _jit_sub(x, zero)
        assert np.array_equal(np.asarray(got), np.asarray(x))

    def test_padd_vs_ec(self, batch):
        a, b = batch
        got = _jit_padd(MP.to_soa(a), MP.to_soa(b))
        want = np.asarray(MP.to_soa(ec.padd(a, b)))
        assert np.array_equal(np.asarray(got), want)

    def test_padd_doubling_and_infinity(self, batch):
        a, _ = batch
        inf = ec.inf_point((a.shape[0],))
        got = _jit_padd(MP.to_soa(a), MP.to_soa(a))
        want = np.asarray(MP.to_soa(ec.padd(a, a)))
        assert np.array_equal(np.asarray(got), want)
        got2 = MP.from_soa(_jit_padd(MP.to_soa(a), MP.to_soa(inf)))
        assert ec.decode_points(got2) == ec.decode_points(a)


class TestLegalBlock:
    def test_lane_multiple_dividing_pad(self):
        # largest multiple of LANE that divides n_pad, capped at `want`
        assert MP._legal_block(128, 2048) == 128
        assert MP._legal_block(256, 2048) == 256
        assert MP._legal_block(384, 256) == 128     # 256 doesn't divide 384
        assert MP._legal_block(4096, 2048) == 2048
        assert MP._legal_block(4096, 100) == 128    # floor is one lane tile
        for n_pad in (128, 384, 1152, 4096):
            b = MP._legal_block(n_pad, 2048)
            assert b % MP.LANE == 0 and n_pad % b == 0


class TestInterpretMode:
    """The REAL pallas_call in interpret mode (auto-selected off-TPU): one
    small shape — the kernel body is already covered by TestKernelMath;
    this pins the pallas_call plumbing (BlockSpecs, grid, the in-trace
    modulus column) against the same ec.padd oracle."""

    def test_interpret_dispatch_off_tpu(self):
        assert MP._interpret() is (jax.default_backend() != "tpu")

    def test_padd_soa_matches_ec(self, batch):
        a, b = batch
        got = MP.from_soa(MP.padd_soa(MP.to_soa(a), MP.to_soa(b)))
        assert np.array_equal(np.asarray(got), np.asarray(ec.padd(a, b)))

    def test_padd_soa_pads_partial_lane_batch(self, batch):
        # n=8 < LANE exercises the pad-to-128 + slice-back path
        a, b = batch
        out = MP.padd_soa(MP.to_soa(a), MP.to_soa(b))
        assert out.shape == (MP.ROWS, a.shape[0])


class TestBucketKernel:
    """The VMEM-resident bucket accumulation (this PR): the pure jnp body
    `_k_bucket_accumulate` is testable without pallas_call, same pattern as
    TestKernelMath; one small-shape test runs the REAL pallas_call pipeline
    in interpret mode."""

    def test_cneg_matches_ec(self, batch):
        a, _ = batch
        soa = MP.to_soa(a)
        mask = jnp.asarray([[True, False] * (a.shape[0] // 2)])
        got = jax.jit(MP._k_cneg)(mask, soa)
        want = MP.to_soa(ec.cneg(mask[0], a))
        assert np.array_equal(np.asarray(got), np.asarray(want))

    def test_cneg_keeps_infinity_at_infinity(self):
        # -(0:1:0) = (0:-1:0): a different representative of the SAME
        # point (Z = 0) — the complete padd treats both as identity
        inf = MP.inf_soa(4)
        got = jax.jit(MP._k_cneg)(jnp.ones((1, 4), bool), inf)
        assert ec.decode_points(MP.from_soa(got)) == [None] * 4
        assert np.array_equal(np.asarray(got[:MP.NL]),
                              np.asarray(inf[:MP.NL]))       # x untouched
        assert np.array_equal(np.asarray(got[2 * MP.NL:]),
                              np.asarray(inf[2 * MP.NL:]))   # z untouched

    @pytest.mark.slow
    def test_accumulate_matches_manual_buckets(self, batch):
        """One window, signed digits + GLV signs: the kernel body's bucket
        array must equal per-bucket ec sums of the (conditionally negated)
        points. slow marker: the nested fori_loop body costs a ~40s
        XLA-CPU compile; `make test` (no marker filter) runs it."""
        a, _ = batch
        n = a.shape[0]
        nb = 4
        digs = jnp.asarray([[1, -2, 0, 2, 4, -1, 2, 3][:n]], jnp.int32)
        negs = jnp.asarray([[0, 1, 0, 0, 1, 0, 0, 1][:n]], jnp.uint32)
        buckets = jnp.broadcast_to(MP.inf_soa(1)[:, :1][None],
                                   (1, MP.ROWS, nb))
        got = jax.jit(MP._k_bucket_accumulate)(
            MP.to_soa(a)[None], digs, negs, buckets)
        eff = ec.cneg(np.asarray(
            (np.asarray(digs)[0] < 0) ^ (np.asarray(negs)[0] != 0)), a)
        for j in range(nb):
            want = ec.inf_point(())
            for i in range(n):
                if abs(int(digs[0, i])) == j + 1:
                    want = ec.padd(eff[i], want)
            assert ec.decode_points(
                MP.from_soa(got[0])[j][None]) == ec.decode_points(
                    jnp.asarray(want)[None])

    @pytest.mark.skipif(not os.environ.get("RUN_SLOW"),
                        reason="85 eager window aggregations (RUN_SLOW=1); "
                               "tier-1 parity lives in test_msm_modes")
    def test_bucket_pipeline_matches_host_msm(self):
        """The REAL pallas_call bucket pipeline (interpret mode) end to
        end: msm_soa (signed recode, VMEM-resident buckets, weighted
        aggregation) vs the host curve."""
        n = 8
        pts = _pts(n, seed=5)
        scalars = [(7919 * k + 13) % bn.R for k in range(n)]
        from spectre_tpu.ops import limbs as L
        soa = MP.to_soa(ec.encode_points(pts))
        sc = jnp.asarray(L.ints_to_limbs16(scalars))
        res = MP.msm_soa(soa, sc, c=3)
        got = ec.decode_points(jnp.asarray(res)[None])[0]
        want = bn.g1_curve.msm(pts, scalars)
        assert (int(got[0]), int(got[1])) == (int(want[0]), int(want[1]))

"""In-circuit hash-to-G2 chip tests (reference: halo2-lib HashToCurveChip).

Default tier: expand_message_xmd + hash_to_field vs the host suite, with a
mock-prove at small k. RUN_SLOW: the full map (SSWU + iso + BP cofactor,
~11M cells) vs the blst-validated host pipeline."""

import os

import pytest

from spectre_tpu.builder import Context, RangeChip
from spectre_tpu.builder.fp_chip import FpChip
from spectre_tpu.builder.fp2_chip import Fp2Chip
from spectre_tpu.builder.fp12_chip import Fp12Chip
from spectre_tpu.builder.hash_to_curve_chip import HashToCurveChip
from spectre_tpu.builder.pairing_chip import PairingChip
from spectre_tpu.builder.sha256_chip import Sha256Chip
from spectre_tpu.fields import bls12_381 as bls
from spectre_tpu.gadgets.ssz_merkle import load_bytes_checked
from spectre_tpu.plonk.mock import mock_prove
from spectre_tpu.spec import DST

RUN_SLOW = os.environ.get("RUN_SLOW") == "1"


def _chip():
    ctx = Context()
    fp2 = Fp2Chip(FpChip(RangeChip(lookup_bits=8)))
    chip = HashToCurveChip(PairingChip(Fp12Chip(fp2)), Sha256Chip())
    return ctx, fp2, chip


class TestExpandAndField:
    def test_expand_message_xmd_vs_host(self):
        msg = b"\x5a" * 32
        ctx, fp2, chip = _chip()
        cells = load_bytes_checked(ctx, chip.sha, msg)
        digs = chip.expand_message_xmd(ctx, cells, DST, 256)
        got = b"".join(
            b"".join(int(w.value).to_bytes(4, "big") for w in d) for d in digs)
        assert got == bls.expand_message_xmd(msg, DST, 256)

    def test_hash_to_field_vs_host_and_mock(self):
        msg = b"\x21" * 32
        ctx, fp2, chip = _chip()
        cells = load_bytes_checked(ctx, chip.sha, msg)
        us = chip.hash_to_field_fq2(ctx, cells, DST)
        want = bls.hash_to_field_fq2(msg, DST)
        for (c0, c1), wv in zip(us, want):
            assert (c0.value % bls.P, c1.value % bls.P) == \
                (int(wv.c[0]), int(wv.c[1]))
        cfg = ctx.auto_config(k=15, lookup_bits=8)
        assert mock_prove(cfg, ctx.assignment(cfg))

    def test_expand_message_xmd_wide_vs_host_and_mock(self):
        """The wide-region expand path (compressions in the bit-ladder
        region, XOR mix on nibbles) produces the same digests and
        mock-satisfies — including the region identities."""
        from spectre_tpu.builder import GateChip
        from spectre_tpu.builder.sha256_wide_chip import Sha256WideChip

        msg = b"\x5a" * 32
        ctx = Context()
        gate = GateChip()
        fp2 = Fp2Chip(FpChip(RangeChip(lookup_bits=8, gate=gate)))
        shaw = Sha256WideChip(gate)
        chip = HashToCurveChip(PairingChip(Fp12Chip(fp2)), Sha256Chip(gate),
                               sha_wide=shaw)
        cells = load_bytes_checked(ctx, shaw, msg)
        digs = chip.expand_message_xmd_wide(ctx, cells, DST, 256)
        got = b"".join(
            b"".join(int(w.value).to_bytes(4, "big") for w in d) for d in digs)
        assert got == bls.expand_message_xmd(msg, DST, 256)
        cfg = ctx.auto_config(k=13, lookup_bits=8)
        assert mock_prove(cfg, ctx.assignment(cfg))

    def test_sgn0_gadget(self):
        ctx, fp2, chip = _chip()
        for v, want in (((2, 0), 0), ((3, 0), 1), ((0, 3), 1), ((0, 2), 0),
                        ((4, 7), 0), ((5, 2), 1)):
            a = chip._canonical_fq2(ctx, fp2.load(ctx, bls.Fq2(list(v))))
            assert chip.sgn0(ctx, a).value == want, v
        cfg = ctx.auto_config(k=13, lookup_bits=8)
        assert mock_prove(cfg, ctx.assignment(cfg))


@pytest.mark.skipif(not RUN_SLOW, reason="~11M cells (set RUN_SLOW=1)")
class TestFullHashToG2:
    def test_full_map_vs_host(self):
        msg = b"\xab" * 32
        ctx, fp2, chip = _chip()
        cells = load_bytes_checked(ctx, chip.sha, msg)
        h = chip.hash_to_g2(ctx, cells, DST)  # built-in oracle assert inside
        want = bls.hash_to_g2(msg, DST)
        assert (fp2.value(h[0]), fp2.value(h[1])) == want

"""Cross-implementation conformance against the reference's 512-entry
fixtures (reference `test_data/*.json`, produced by its Rust+blst test-data
generator — SURVEY.md §4 'deterministic fixture generation').

Fast tier: native verification (BLS aggregate signature over SSWU
hash-to-curve, SSZ merkle branches, instance computation) — this is the
interop proof for the whole host stack. RUN_SLOW tier: full in-circuit
witness builds at committee size 512."""

import os

import pytest

from spectre_tpu import spec as SP
from spectre_tpu.fields import bls12_381 as bls
from spectre_tpu.gadgets.ssz_merkle import verify_merkle_proof_native
from spectre_tpu.models import CommitteeUpdateCircuit, StepCircuit
from spectre_tpu.witness import ref_fixtures as RF

REF = "/root/reference/test_data"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference fixtures not mounted")


@pytest.fixture(scope="module")
def step_args():
    return RF.load_sync_step(os.path.join(REF, "sync_step_512.json"))


@pytest.fixture(scope="module")
def rotation_args():
    return RF.load_rotation(os.path.join(REF, "rotation_512.json"))


class TestNativeConformance:
    def test_step_signature_verifies(self, step_args):
        """The fixture's blst-made aggregate signature must verify through
        this framework's from-scratch SSWU + pairing stack."""
        a = step_args
        assert len(a.pubkeys_uncompressed) == SP.MAINNET.sync_committee_size
        pts = [(bls.Fq(x), bls.Fq(y)) for (x, y), b in
               zip(a.pubkeys_uncompressed, a.participation_bits) if b]
        sig = bls.g2_decompress(a.signature_compressed)
        assert bls.fast_aggregate_verify(pts, a.signing_root(), sig,
                                         dst=SP.MAINNET.dst)

    def test_step_signature_rejects_wrong_root(self, step_args):
        a = step_args
        pts = [(bls.Fq(x), bls.Fq(y)) for (x, y), b in
               zip(a.pubkeys_uncompressed, a.participation_bits) if b]
        sig = bls.g2_decompress(a.signature_compressed)
        assert not bls.fast_aggregate_verify(pts, b"\x55" * 32, sig,
                                             dst=SP.MAINNET.dst)

    def test_step_branches_verify(self, step_args):
        a = step_args
        assert verify_merkle_proof_native(
            a.finalized_header.hash_tree_root(), a.finality_branch,
            SP.MAINNET.finalized_header_index, a.attested_header.state_root)
        assert verify_merkle_proof_native(
            a.execution_payload_root, a.execution_payload_branch,
            SP.MAINNET.execution_state_root_index,
            a.finalized_header.body_root)

    def test_rotation_branch_verifies(self, rotation_args):
        a = rotation_args
        assert len(a.pubkeys_compressed) == SP.MAINNET.sync_committee_size
        assert verify_merkle_proof_native(
            a.committee_pubkeys_root(), a.sync_committee_branch,
            SP.MAINNET.sync_committee_pubkeys_root_index,
            a.finalized_header.state_root)

    def test_rotation_pubkeys_decompress_and_match_step(self, step_args,
                                                        rotation_args):
        """Both fixtures describe the same committee: decompressing the
        rotation pubkeys must yield the step fixture's uncompressed points."""
        for pk_c, (x, y) in zip(rotation_args.pubkeys_compressed,
                                step_args.pubkeys_uncompressed):
            pt = bls.g1_decompress(pk_c)
            assert (int(pt[0]), int(pt[1])) == (x, y)

    def test_instances_compute(self, step_args, rotation_args):
        si = StepCircuit.get_instances(step_args, SP.MAINNET)
        ci = CommitteeUpdateCircuit.get_instances(rotation_args, SP.MAINNET)
        assert len(si) == 2 and len(ci) == 3
        # the committee poseidon is shared across the two circuits'
        # statements (reference asserts the same, `tests/step.rs:113-116`)
        assert si[1] == ci[0]


@pytest.mark.skipif(not os.environ.get("RUN_SLOW"),
                    reason="512-entry circuit builds (set RUN_SLOW=1)")
class TestCircuitConformance:
    def test_committee_update_512_witness_and_instances(self, rotation_args):
        ctx = CommitteeUpdateCircuit.build_context(rotation_args, SP.MAINNET)
        got = [c.value for c in ctx.instance_cells]
        assert got == CommitteeUpdateCircuit.get_instances(rotation_args,
                                                           SP.MAINNET)

    def test_step_512_witness_and_instances(self, step_args):
        ctx = StepCircuit.build_context(step_args, SP.MAINNET)
        got = [c.value for c in ctx.instance_cells]
        assert got == StepCircuit.get_instances(step_args, SP.MAINNET)

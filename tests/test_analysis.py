"""Static-analysis subsystem (spectre_tpu.analysis): finding/baseline
mechanics, circuit-audit rules, kernel-lint rules — including the seeded
MUTATION checks: a deliberately under-constrained cell, an over-degree
expression, and a limb-overflow multiply must each be flagged (the
auditor's reason to exist is that nothing else notices these)."""

import json
import random

import numpy as np
import pytest

from spectre_tpu.analysis import (Finding, Severity, audit_context,
                                  load_baseline, partition_findings,
                                  write_baseline)
from spectre_tpu.analysis.circuit_audit import expression_degrees
from spectre_tpu.analysis.kernel_lint import (KERNELS, lint_fn, lint_kernel,
                                              lint_limbs_host)
from spectre_tpu.builder.context import Context
from spectre_tpu.builder.range_chip import RangeChip
from spectre_tpu.plonk.constraint_system import CircuitConfig
from spectre_tpu.plonk.expressions import all_expressions


def _small_circuit():
    """A clean little range-checked multiply circuit."""
    random.seed(0)
    ctx = Context()
    rng = RangeChip(lookup_bits=4)
    g = rng.gate
    a = ctx.load_witness(3)
    b = ctx.load_witness(5)
    c = g.mul(ctx, a, b)
    rng.range_check(ctx, a, 4)
    ctx.expose_public(c)
    cfg = ctx.auto_config(k=7, lookup_bits=4)
    return ctx, cfg


class TestFindings:
    def test_key_defaults_and_partition(self):
        f1 = Finding("circuit", "CA-X", Severity.ERROR, "f.py", "obj", "m")
        assert f1.key == "CA-X:obj"
        f2 = Finding("circuit", "CA-Y", Severity.WARNING, "f.py", "obj", "m",
                     key="CA-Y:obj:7")
        active, suppressed = partition_findings(
            [f1, f2], {"CA-Y:obj:7": "accepted"})
        assert active == [f1] and suppressed == [f2]

    def test_baseline_roundtrip(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        f = Finding("kernel", "KL-X", Severity.ERROR, "f.py", "k", "msg",
                    key="KL-X:k:1")
        write_baseline([f], path, reason="test")
        bl = load_baseline(path)
        assert "KL-X:k:1" in bl
        with open(path) as fh:
            assert json.load(fh)["suppressions"][0]["key"] == "KL-X:k:1"

    def test_severity_order(self):
        assert Severity.at_least("error", "warning")
        assert not Severity.at_least("warning", "error")


class TestCircuitAudit:
    def test_clean_circuit_has_no_findings(self):
        ctx, cfg = _small_circuit()
        assert audit_context(ctx, cfg, "clean") == []

    def test_flags_seeded_underconstrained_cell(self):
        """THE mutation check: a witness cell no constraint touches."""
        ctx, cfg = _small_circuit()
        ctx.load_witness(999)  # assigned, never referenced by anything
        cfg2 = ctx.auto_config(k=7, lookup_bits=4)
        rules = [f.rule for f in audit_context(ctx, cfg2, "seeded")]
        assert "CA-UNDERCONSTRAINED" in rules

    def test_flags_seeded_degree_violation(self):
        """Injected expression of column-degree 5 > budget 4."""
        ctx, cfg = _small_circuit()

        def evil(cfg_, c, beta, gamma):
            yield from all_expressions(cfg_, c, beta, gamma)
            v = c.var(("adv", 0), 0)
            yield c.mul(c.mul(c.mul(c.mul(v, v), v), v), v)

        fs = audit_context(ctx, cfg, "deg", expressions_fn=evil)
        assert any(f.rule == "CA-DEGREE" for f in fs)
        # the real expression set stays inside the budget
        assert all(d <= cfg.max_expr_degree for d in expression_degrees(cfg))

    def test_real_expression_degrees_within_budget(self):
        # incl. the wide-SHA region identities (selector x bit-cubics)
        cfg = CircuitConfig(k=10, num_advice=1, num_lookup_advice=1,
                            num_fixed=1, lookup_bits=8, num_sha_slots=1)
        degs = expression_degrees(cfg)
        assert degs and max(degs) <= cfg.max_expr_degree

    def test_flags_copy_orphan(self):
        ctx, cfg = _small_circuit()
        ctx.copies.append((("adv", 10 ** 6), ("adv", 0)))
        fs = audit_context(ctx, cfg, "orphan")
        assert any(f.rule == "CA-COPY-ORPHAN" for f in fs)

    def test_flags_unbound_lookup_table(self):
        ctx, _ = _small_circuit()
        ctx.lkp_streams.setdefault("nibble_op", []).append(5)
        cfg = CircuitConfig(k=7, num_advice=2, num_lookup_advice=1,
                            num_fixed=1, lookup_bits=4,
                            lookup_tables=("range",))
        fs = audit_context(ctx, cfg, "tbl")
        assert any(f.rule == "CA-TABLE-UNBOUND" for f in fs)

    def test_flags_dead_columns(self):
        ctx = Context()
        v = ctx.load_witness(5)
        ctx.expose_public(v)  # referenced, so not under-constrained
        cfg = CircuitConfig(k=7, num_advice=1, num_lookup_advice=1,
                            num_fixed=1, lookup_bits=4)
        rules = [f.rule for f in audit_context(ctx, cfg, "dead")]
        assert "CA-DEAD-SELECTOR" in rules and "CA-DEAD-FIXED" in rules


class TestKernelLint:
    def test_flags_seeded_limb_overflow_multiply(self):
        """THE mutation check: 17-bit limbs leave no headroom in u32."""
        import jax.numpy as jnp
        a = jnp.zeros((4, 16), jnp.uint32)
        fs = lint_fn(lambda x, y: x * y, (a, a), name="mut.widemul",
                     file="x.py", in_bits=17)
        assert [f.rule for f in fs] == ["KL-OVERFLOW"]
        # 16-bit limbs fit exactly: (2^16-1)^2 < 2^32
        assert lint_fn(lambda x, y: x * y, (a, a), name="mut.mul16",
                       file="x.py", in_bits=16) == []

    def test_mask_consumed_product_is_exempt(self):
        import jax.numpy as jnp
        a = jnp.zeros((4, 16), jnp.uint32)
        fs = lint_fn(lambda x, y: (x * y) & np.uint32(0xFFFF), (a, a),
                     name="mut.masked", file="x.py", in_bits=17)
        assert fs == []

    def test_flags_unreduced_add_chain(self):
        import jax.numpy as jnp
        a = jnp.zeros((4, 16), jnp.uint32)

        def chain(x):
            acc = x
            for _ in range(17):  # 2^17 summands of 2^16-1 overflow u32
                acc = acc + acc
            return acc

        fs = lint_fn(chain, (a,), name="mut.chain", file="x.py", in_bits=16)
        assert any(f.rule == "KL-OVERFLOW" for f in fs)

    def test_flags_float_in_field_kernel(self):
        import jax.numpy as jnp
        a = jnp.zeros((4, 16), jnp.uint32)
        fs = lint_fn(lambda x: (x.astype(jnp.float32) * 2.0)
                     .astype(jnp.uint32),
                     (a,), name="mut.float", file="x.py")
        assert any(f.rule == "KL-FLOAT" for f in fs)

    def test_flags_host_callback(self):
        import jax
        import jax.numpy as jnp
        a = jnp.zeros((4, 16), jnp.uint32)

        def cb(x):
            return jax.pure_callback(
                lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

        fs = lint_fn(cb, (a,), name="mut.cb", file="x.py")
        assert any(f.rule == "KL-CALLBACK" for f in fs)

    def test_real_field_kernels_clean(self):
        for spec in KERNELS:
            if spec.name in ("field_ops.mont_mul", "field_ops.add",
                             "ntt.ntt", "sha256.compress"):
                assert lint_kernel(spec) == [], spec.name

    def test_limbs_host_probe_clean(self):
        assert lint_limbs_host() == []


class TestCLI:
    def test_kernel_engine_exit_clean(self, tmp_path, capsys):
        from spectre_tpu.analysis.__main__ import main
        out = str(tmp_path / "findings.json")
        rc = main(["--engine", "kernel", "--kernels",
                   "field_ops.add,limbs.host", "--json", out, "-q"])
        assert rc == 0
        data = json.load(open(out))
        assert data["active"] == []

    def test_fail_on_gates_exit_code(self, tmp_path, monkeypatch):
        """A seeded finding must flip the exit code unless baselined."""
        from spectre_tpu.analysis import __main__ as M
        from spectre_tpu.analysis import kernel_lint as KL
        import jax.numpy as jnp

        def fake_all(names=None):
            a = jnp.zeros((2, 16), jnp.uint32)
            return lint_fn(lambda x, y: x * y, (a, a), name="mut.cli",
                           file="x.py", in_bits=17)

        monkeypatch.setattr(KL, "lint_all_kernels", fake_all)
        empty = str(tmp_path / "empty.json")
        rc = M.main(["--engine", "kernel", "--baseline", empty, "-q"])
        assert rc == 1
        # accept into a baseline -> clean
        bl = str(tmp_path / "bl.json")
        assert M.main(["--engine", "kernel", "--baseline", bl,
                       "--write-baseline", "-q"]) == 0
        assert M.main(["--engine", "kernel", "--baseline", bl, "-q"]) == 0


class TestShippedBaseline:
    def test_repo_baseline_still_empty(self):
        """ISSUE 6 satellite: the shipped analysis baseline must stay EMPTY
        — a suppression sneaking in here would silently accept a real
        circuit-soundness or kernel-lint finding. Grow it only with an
        explicit, reviewed `--write-baseline` run."""
        import os

        import spectre_tpu.analysis as A
        path = os.path.join(os.path.dirname(A.__file__), "baseline.json")
        with open(path) as fh:
            data = json.load(fh)
        assert data == {"suppressions": []}

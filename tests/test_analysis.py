"""Static-analysis subsystem (spectre_tpu.analysis): finding/baseline
mechanics, circuit-audit rules, kernel-lint rules, trace-cache hygiene
rules — including the seeded MUTATION checks: a deliberately
under-constrained cell, an over-degree expression, a limb-overflow
multiply, a fresh-per-call jit, and a row-level coverage hole must each
be flagged (the auditor's reason to exist is that nothing else notices
these), while the clean live tree produces ZERO findings."""

import dataclasses
import json
import random
import time

import numpy as np
import pytest

from spectre_tpu.analysis import (Finding, Severity, audit_context,
                                  audit_rows, load_baseline,
                                  partition_findings, write_baseline)
from spectre_tpu.analysis.circuit_audit import expression_degrees
from spectre_tpu.analysis.kernel_lint import (KERNELS, lint_fn, lint_kernel,
                                              lint_limbs_host)
from spectre_tpu.builder.context import Context
from spectre_tpu.builder.range_chip import RangeChip
from spectre_tpu.plonk.constraint_system import CircuitConfig
from spectre_tpu.plonk.expressions import all_expressions


def _small_circuit():
    """A clean little range-checked multiply circuit."""
    random.seed(0)
    ctx = Context()
    rng = RangeChip(lookup_bits=4)
    g = rng.gate
    a = ctx.load_witness(3)
    b = ctx.load_witness(5)
    c = g.mul(ctx, a, b)
    rng.range_check(ctx, a, 4)
    ctx.expose_public(c)
    cfg = ctx.auto_config(k=7, lookup_bits=4)
    return ctx, cfg


class TestFindings:
    def test_key_defaults_and_partition(self):
        f1 = Finding("circuit", "CA-X", Severity.ERROR, "f.py", "obj", "m")
        assert f1.key == "CA-X:obj"
        f2 = Finding("circuit", "CA-Y", Severity.WARNING, "f.py", "obj", "m",
                     key="CA-Y:obj:7")
        active, suppressed = partition_findings(
            [f1, f2], {"CA-Y:obj:7": "accepted"})
        assert active == [f1] and suppressed == [f2]

    def test_baseline_roundtrip(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        f = Finding("kernel", "KL-X", Severity.ERROR, "f.py", "k", "msg",
                    key="KL-X:k:1")
        write_baseline([f], path, reason="test")
        bl = load_baseline(path)
        assert "KL-X:k:1" in bl
        with open(path) as fh:
            assert json.load(fh)["suppressions"][0]["key"] == "KL-X:k:1"

    def test_severity_order(self):
        assert Severity.at_least("error", "warning")
        assert not Severity.at_least("warning", "error")


class TestCircuitAudit:
    def test_clean_circuit_has_no_findings(self):
        ctx, cfg = _small_circuit()
        assert audit_context(ctx, cfg, "clean") == []

    def test_flags_seeded_underconstrained_cell(self):
        """THE mutation check: a witness cell no constraint touches."""
        ctx, cfg = _small_circuit()
        ctx.load_witness(999)  # assigned, never referenced by anything
        cfg2 = ctx.auto_config(k=7, lookup_bits=4)
        rules = [f.rule for f in audit_context(ctx, cfg2, "seeded")]
        assert "CA-UNDERCONSTRAINED" in rules

    def test_flags_seeded_degree_violation(self):
        """Injected expression of column-degree 5 > budget 4."""
        ctx, cfg = _small_circuit()

        def evil(cfg_, c, beta, gamma):
            yield from all_expressions(cfg_, c, beta, gamma)
            v = c.var(("adv", 0), 0)
            yield c.mul(c.mul(c.mul(c.mul(v, v), v), v), v)

        fs = audit_context(ctx, cfg, "deg", expressions_fn=evil)
        assert any(f.rule == "CA-DEGREE" for f in fs)
        # the real expression set stays inside the budget
        assert all(d <= cfg.max_expr_degree for d in expression_degrees(cfg))

    def test_real_expression_degrees_within_budget(self):
        # incl. the wide-SHA region identities (selector x bit-cubics)
        cfg = CircuitConfig(k=10, num_advice=1, num_lookup_advice=1,
                            num_fixed=1, lookup_bits=8, num_sha_slots=1)
        degs = expression_degrees(cfg)
        assert degs and max(degs) <= cfg.max_expr_degree

    def test_flags_copy_orphan(self):
        ctx, cfg = _small_circuit()
        ctx.copies.append((("adv", 10 ** 6), ("adv", 0)))
        fs = audit_context(ctx, cfg, "orphan")
        assert any(f.rule == "CA-COPY-ORPHAN" for f in fs)

    def test_flags_unbound_lookup_table(self):
        ctx, _ = _small_circuit()
        ctx.lkp_streams.setdefault("nibble_op", []).append(5)
        cfg = CircuitConfig(k=7, num_advice=2, num_lookup_advice=1,
                            num_fixed=1, lookup_bits=4,
                            lookup_tables=("range",))
        fs = audit_context(ctx, cfg, "tbl")
        assert any(f.rule == "CA-TABLE-UNBOUND" for f in fs)

    def test_flags_dead_columns(self):
        ctx = Context()
        v = ctx.load_witness(5)
        ctx.expose_public(v)  # referenced, so not under-constrained
        cfg = CircuitConfig(k=7, num_advice=1, num_lookup_advice=1,
                            num_fixed=1, lookup_bits=4)
        rules = [f.rule for f in audit_context(ctx, cfg, "dead")]
        assert "CA-DEAD-SELECTOR" in rules and "CA-DEAD-FIXED" in rules


class TestRowAudit:
    """Row-wise gate-coverage auditor (ISSUE 16 tentpole): coverage holes
    in the PHYSICAL assignment grid that the stream-level rules miss."""

    def test_clean_circuit_rows_clean(self):
        ctx, cfg = _small_circuit()
        assert audit_rows(ctx, cfg, "clean") == []

    def test_flags_seeded_row_unbound(self):
        """THE row-level mutation: a placed cell drifts to a row no gate
        window covers and no copy endpoint binds — a free witness row."""
        ctx, cfg = _small_circuit()

        def mutate(placement, selectors, copies):
            placement[max(placement)] = (0, cfg.usable_rows - 2)
            return placement, selectors, copies

        fs = audit_rows(ctx, cfg, "rowmut", mutate=mutate)
        assert any(f.rule == "CA-ROW-UNBOUND"
                   and f.severity == Severity.ERROR for f in fs)

    def test_flags_seeded_dead_selector_row(self):
        """A selector armed over rows its gate window never reads from."""
        ctx, cfg = _small_circuit()

        def mutate(placement, selectors, copies):
            selectors[0][cfg.usable_rows - 8] = 1
            return placement, selectors, copies

        fs = audit_rows(ctx, cfg, "deadsel", mutate=mutate)
        assert any(f.rule == "CA-ROW-DEAD-SELECTOR" for f in fs)

    def test_flags_stale_sha_slot_selectors(self):
        """Config allocates a SHA slot the circuit never fills: the
        structural selectors gate all-zero rows — vacuous activation."""
        ctx, cfg = _small_circuit()
        cfg2 = dataclasses.replace(cfg, num_sha_slots=1)
        fs = audit_rows(ctx, cfg2, "shastale")
        assert any(f.rule == "CA-ROW-DEAD-SELECTOR" and ":sha" in f.key
                   for f in fs)

    def test_row_mutate_does_not_poison_caches(self):
        """The mutate hook gets copies: a seeded mutant must not leak
        into the Context's layout/placement caches."""
        ctx, cfg = _small_circuit()

        def mutate(placement, selectors, copies):
            placement[max(placement)] = (0, cfg.usable_rows - 2)
            selectors[0][0] = 0
            return placement, selectors, copies

        assert audit_rows(ctx, cfg, "m", mutate=mutate) != []
        assert audit_rows(ctx, cfg, "clean-again") == []

    def test_audit_context_threads_row_mutate(self):
        ctx, cfg = _small_circuit()

        def mutate(placement, selectors, copies):
            placement[max(placement)] = (0, cfg.usable_rows - 2)
            return placement, selectors, copies

        rules = [f.rule for f in audit_context(ctx, cfg, "threaded",
                                               row_mutate=mutate)]
        assert "CA-ROW-UNBOUND" in rules


class TestKernelLint:
    def test_flags_seeded_limb_overflow_multiply(self):
        """THE mutation check: 17-bit limbs leave no headroom in u32."""
        import jax.numpy as jnp
        a = jnp.zeros((4, 16), jnp.uint32)
        fs = lint_fn(lambda x, y: x * y, (a, a), name="mut.widemul",
                     file="x.py", in_bits=17)
        assert [f.rule for f in fs] == ["KL-OVERFLOW"]
        # 16-bit limbs fit exactly: (2^16-1)^2 < 2^32
        assert lint_fn(lambda x, y: x * y, (a, a), name="mut.mul16",
                       file="x.py", in_bits=16) == []

    def test_mask_consumed_product_is_exempt(self):
        import jax.numpy as jnp
        a = jnp.zeros((4, 16), jnp.uint32)
        fs = lint_fn(lambda x, y: (x * y) & np.uint32(0xFFFF), (a, a),
                     name="mut.masked", file="x.py", in_bits=17)
        assert fs == []

    def test_flags_unreduced_add_chain(self):
        import jax.numpy as jnp
        a = jnp.zeros((4, 16), jnp.uint32)

        def chain(x):
            acc = x
            for _ in range(17):  # 2^17 summands of 2^16-1 overflow u32
                acc = acc + acc
            return acc

        fs = lint_fn(chain, (a,), name="mut.chain", file="x.py", in_bits=16)
        assert any(f.rule == "KL-OVERFLOW" for f in fs)

    def test_flags_float_in_field_kernel(self):
        import jax.numpy as jnp
        a = jnp.zeros((4, 16), jnp.uint32)
        fs = lint_fn(lambda x: (x.astype(jnp.float32) * 2.0)
                     .astype(jnp.uint32),
                     (a,), name="mut.float", file="x.py")
        assert any(f.rule == "KL-FLOAT" for f in fs)

    def test_flags_host_callback(self):
        import jax
        import jax.numpy as jnp
        a = jnp.zeros((4, 16), jnp.uint32)

        def cb(x):
            return jax.pure_callback(
                lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

        fs = lint_fn(cb, (a,), name="mut.cb", file="x.py")
        assert any(f.rule == "KL-CALLBACK" for f in fs)

    def test_real_field_kernels_clean(self):
        for spec in KERNELS:
            if spec.name in ("field_ops.mont_mul", "field_ops.add",
                             "ntt.ntt", "sha256.compress"):
                assert lint_kernel(spec) == [], spec.name

    def test_limbs_host_probe_clean(self):
        assert lint_limbs_host() == []


# --------------------------------------------------------------------------
# trace-cache hygiene lint (ISSUE 16 tentpole)
# --------------------------------------------------------------------------

# regression fixture: the pre-ISSUE-13 sharded_msm shape — a fresh
# shard_map closure wrapped in a fresh jit on EVERY call (the MULTICHIP
# rc=124 root cause)
_FRESH_SHARD_SRC = '''\
import functools

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def sharded_msm(points, scalars, c, mesh):
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P("data"), P("data")), out_specs=P())
    def run(p, s):
        return (p * s).sum()

    return jax.jit(run)(points, scalars)
'''

_EXEMPT_SRC = '''\
import functools

import jax

_RUNNERS = {}

TRACE_RUNNER_CACHES = (("_get_runner", "_RUNNERS"),)


def _get_runner(c):
    fn = _RUNNERS.get(c)
    if fn is None:
        fn = jax.jit(lambda x: x * c)
        _RUNNERS[c] = fn
    return fn


@functools.cache
def _memo_runner(c):
    return jax.jit(lambda x: x + c)


@jax.jit
def entry(x):
    return jax.jit(lambda v: v)(x)
'''

_CONSTCAP_SRC = '''\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LUT = jnp.arange(8)


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] + _LUT


@jax.jit
def call(x):
    return pl.pallas_call(_kernel, out_shape=x)(x)
'''

_UNSTABLE_SRC = '''\
import functools

import jax


@functools.partial(jax.jit, static_argnums=(1,), static_argnames=("mode",))
def kernel(x, shape, mode="std"):
    return x


def caller(x):
    return kernel(x, [4, 4], mode="std")


def caller2(x):
    return kernel(x, (4, 4), mode={"a": 1})
'''

_UNDECLARED_SRC = '''\
import jax

_RUNNERS = {}


def _build(key):
    fn = jax.jit(lambda x: x)
    _RUNNERS[key] = fn
    return fn
'''

_STALE_SRC = '''\
import jax

_RUNNERS = {}

TRACE_RUNNER_CACHES = (("_vanished", "_RUNNERS"),)
TRACE_JIT_ROOTS = ("also_gone",)
'''


def _scan_src(tmp_path, src, name="fixture_mod.py"):
    from spectre_tpu.analysis.trace_lint import scan_files
    p = tmp_path / name
    p.write_text(src)
    return scan_files([str(p)])


class TestTraceLintStatic:
    def test_live_tree_static_scan_clean(self):
        """The whole ops/ + parallel/ + plonk/ tree honors the trace-cache
        discipline: zero findings, no baseline entries needed."""
        from spectre_tpu.analysis.trace_lint import scan_files
        assert scan_files() == []

    def test_fresh_jit_regression_fixture(self, tmp_path):
        """ISSUE 16 satellite: the PR 13 fresh-closure shard_map pattern,
        re-created in a throwaway module, trips TC-FRESH-JIT and NOTHING
        else."""
        fs = _scan_src(tmp_path, _FRESH_SHARD_SRC)
        assert fs and {f.rule for f in fs} == {"TC-FRESH-JIT"}
        assert {f.severity for f in fs} == {Severity.ERROR}
        assert all("sharded_msm" in f.key for f in fs)
        # both constructions inside the body are flagged: the shard_map
        # decorator closure AND the per-call jit wrap
        assert {k for _, _, k in
                (f.key.rsplit(":", 2) for f in fs)} == {"jit", "shard_map"}

    def test_fresh_jit_exemptions(self, tmp_path):
        """Runner-cache stores, functools.cache builders, and jit-inside-
        jit (outer jit caches the trace) are NOT fresh-jit findings."""
        assert _scan_src(tmp_path, _EXEMPT_SRC) == []

    def test_flags_pallas_const_capture(self, tmp_path):
        """THE mutation check for the PR 15 class: a kernel body reading a
        module-level concrete-array binding."""
        fs = _scan_src(tmp_path, _CONSTCAP_SRC)
        assert {f.rule for f in fs} == {"TC-CONST-CAPTURE"}
        assert "_LUT" in fs[0].key

    def test_flags_unstable_static_args(self, tmp_path):
        fs = _scan_src(tmp_path, _UNSTABLE_SRC)
        assert {f.rule for f in fs} == {"TC-UNSTABLE-STATIC"}
        assert len(fs) == 2  # list at static position, dict static kwarg

    def test_flags_undeclared_runner_cache(self, tmp_path):
        fs = _scan_src(tmp_path, _UNDECLARED_SRC)
        assert {f.rule for f in fs} == {"TC-UNCACHED-RUNNER"}
        assert fs[0].key.endswith("_build:_RUNNERS")

    def test_flags_stale_registry_entries(self, tmp_path):
        fs = _scan_src(tmp_path, _STALE_SRC)
        assert {f.rule for f in fs} == {"TC-UNCACHED-RUNNER"}
        keys = sorted(f.key for f in fs)
        assert any(k.endswith(":stale") for k in keys)
        assert any(k.endswith(":root") for k in keys)

    def test_registry_ast_matches_live_imports(self):
        """The AST view of TRACE_RUNNER_CACHES (what the lint scans) and
        the live-import view (plan.runner_registry) agree module by
        module — declarative drift in either direction is a failure."""
        import ast
        import importlib

        from spectre_tpu.analysis.trace_lint import _module_toplevel
        from spectre_tpu.parallel.plan import runner_registry
        live = runner_registry()
        assert live  # contract has participants
        for modname, declared in live.items():
            path = importlib.import_module(modname).__file__
            with open(path) as fh:
                tree = ast.parse(fh.read())
            _n, _a, ast_pairs, _r = _module_toplevel(tree)
            assert set(declared) == ast_pairs, modname
            assert declared, f"{modname} declares no runner caches"


class TestTraceLintDynamic:
    def test_retrace_probe_flags_fresh_jit(self):
        """THE dynamic mutation check: a runner that mints a fresh jit per
        call compiles on the second call -> TC-RETRACE-DYN."""
        import jax
        import jax.numpy as jnp

        from spectre_tpu.analysis.trace_lint import ProbeSpec, run_probe

        def build():
            x = jnp.zeros((4,), jnp.uint32)

            def run(v):
                return jax.jit(lambda t: t + jnp.uint32(1))(v)

            return run, (x,)

        fs = run_probe(ProbeSpec("mutant.fresh", "x.py", build))
        assert [f.rule for f in fs] == ["TC-RETRACE-DYN"]
        assert fs[0].key == "TC-RETRACE-DYN:mutant.fresh"
        assert fs[0].severity == Severity.ERROR

    @pytest.mark.slow
    def test_probes_clean_and_within_budget(self):
        """ISSUE 16 satellite: the full probe suite (every registered
        runner family, double-called at tiny shapes) is clean on the live
        tree AND fits the 120s lint-deep budget on a 1-core CPU host.

        slow-marked: ~90s of probe compiles on the 1-core box — runs in
        `make test` (no marker filter; lint-deep also drives the same
        probes there), stays out of the 870s tier-1 window."""
        from spectre_tpu.analysis.trace_lint import PROBES, run_probes
        assert len(PROBES) == 7
        t0 = time.monotonic()
        fs = run_probes()
        dt = time.monotonic() - t0
        assert fs == [], [f.key for f in fs]
        assert dt < 120, f"probe suite took {dt:.1f}s (budget 120s)"


class TestCLI:
    def test_kernel_engine_exit_clean(self, tmp_path, capsys):
        from spectre_tpu.analysis.__main__ import main
        out = str(tmp_path / "findings.json")
        rc = main(["--engine", "kernel", "--kernels",
                   "field_ops.add,limbs.host", "--json", out, "-q"])
        assert rc == 0
        data = json.load(open(out))
        assert data["active"] == []

    def test_trace_engine_json_payload(self, tmp_path):
        """ISSUE 16 satellite: --json is machine-readable — findings plus
        per-pass runtimes plus per-engine root counts."""
        from spectre_tpu.analysis.__main__ import main
        out = str(tmp_path / "trace.json")
        rc = main(["--engine", "trace", "--no-probes", "--json", out, "-q"])
        assert rc == 0
        data = json.load(open(out))
        assert data["active"] == [] and data["suppressed"] == []
        names = [p["name"] for p in data["passes"]]
        assert names == ["trace static scan"]
        p = data["passes"][0]
        assert p["engine"] == "trace" and p["findings"] == 0
        assert isinstance(p["seconds"], float)
        assert data["roots"]["trace_files"] > 10
        assert data["roots"]["trace_probes"] == 0  # --no-probes
        assert data["seconds"] >= p["seconds"]

    def test_trace_engine_fail_on_gates_exit(self, tmp_path, monkeypatch):
        """A seeded trace finding flips the trace-engine exit code."""
        from spectre_tpu.analysis import __main__ as M
        from spectre_tpu.analysis import trace_lint as TL

        def fake_scan(paths=None):
            return [Finding("trace", "TC-FRESH-JIT", Severity.ERROR,
                            "x.py", "m:f", "seeded",
                            key="TC-FRESH-JIT:x.py:f:jit")]

        monkeypatch.setattr(TL, "scan_files", fake_scan)
        monkeypatch.setattr(TL, "PROBES", [])
        empty = str(tmp_path / "empty.json")
        assert M.main(["--engine", "trace", "--baseline", empty, "-q"]) == 1
        bl = str(tmp_path / "bl.json")
        assert M.main(["--engine", "trace", "--baseline", bl,
                       "--write-baseline", "-q"]) == 0
        assert M.main(["--engine", "trace", "--baseline", bl, "-q"]) == 0

    def test_fail_on_gates_exit_code(self, tmp_path, monkeypatch):
        """A seeded finding must flip the exit code unless baselined."""
        from spectre_tpu.analysis import __main__ as M
        from spectre_tpu.analysis import kernel_lint as KL
        import jax.numpy as jnp

        def fake_all(names=None):
            a = jnp.zeros((2, 16), jnp.uint32)
            return lint_fn(lambda x, y: x * y, (a, a), name="mut.cli",
                           file="x.py", in_bits=17)

        monkeypatch.setattr(KL, "lint_all_kernels", fake_all)
        empty = str(tmp_path / "empty.json")
        rc = M.main(["--engine", "kernel", "--baseline", empty, "-q"])
        assert rc == 1
        # accept into a baseline -> clean
        bl = str(tmp_path / "bl.json")
        assert M.main(["--engine", "kernel", "--baseline", bl,
                       "--write-baseline", "-q"]) == 0
        assert M.main(["--engine", "kernel", "--baseline", bl, "-q"]) == 0


class TestShippedBaseline:
    def test_repo_baseline_still_empty(self):
        """ISSUE 6 satellite: the shipped analysis baseline must stay EMPTY
        — a suppression sneaking in here would silently accept a real
        circuit-soundness or kernel-lint finding. Grow it only with an
        explicit, reviewed `--write-baseline` run."""
        import os

        import spectre_tpu.analysis as A
        path = os.path.join(os.path.dirname(A.__file__), "baseline.json")
        with open(path) as fh:
            data = json.load(fh)
        assert data == {"suppressions": []}

    def test_new_passes_need_no_baseline(self):
        """ISSUE 16 satellite: the trace scan and the row auditor landed
        against the EMPTY shipped baseline — the live tree is clean under
        both new passes without a single suppression."""
        from spectre_tpu.analysis.circuit_audit import audit_rows as AR
        from spectre_tpu.analysis.circuits import AUDIT_CIRCUITS
        from spectre_tpu.analysis.trace_lint import scan_files
        assert scan_files() == []
        ctx, cfg, name = AUDIT_CIRCUITS["committee_update"]()
        assert AR(ctx, cfg, name) == []

    def test_matmul_cap_proof_needs_no_baseline(self):
        """ISSUE 19: the closed-form exactness proof of the shipped
        `_MATMUL_MAX_LOGN` (two-level carry split + 2^272 REDC) holds
        against the EMPTY baseline — the cap is proven, not asserted."""
        from spectre_tpu.analysis.kernel_lint import lint_matmul_cap
        from spectre_tpu.ops.ntt import _MATMUL_MAX_LOGN
        assert _MATMUL_MAX_LOGN >= 12
        assert lint_matmul_cap() == []


class TestBenchFloorGuard:
    """ISSUE 17 satellite: the Pallas MSM path must never regress the
    default (xla) path. bench-fast gates measured throughput against
    bench_floor.json at >20% — this pins the floors THEMSELVES, so the
    pallas work can't silently ride in by lowering a checked-in xla floor
    (the one edit the runtime gate can't see)."""

    XLA_FLOORS = {
        "bn254_msm_2^12_cpu_points_per_s": 1058,
        "bn254_ntt_2^12_cpu_polys_per_s": 7.5,
        "bn254_msm_2^12_multichip8_points_per_s": 79,
        "gateway_serve_requests_per_s": 25000,
        "quotient_k11_cpu_per_s": 0.2,
        "quotient_k13_multichip8_per_s": 0.04,
    }

    def test_xla_floors_unchanged(self):
        import os

        import spectre_tpu
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(spectre_tpu.__file__)))
        with open(os.path.join(root, "bench_floor.json")) as fh:
            floors = json.load(fh)
        for key, want in self.XLA_FLOORS.items():
            assert floors.get(key) == want, \
                f"checked-in floor {key} changed (was {want})"

    def test_floor_gate_measures_default_impl(self, monkeypatch):
        """The floors are xla-impl numbers: with no SPECTRE_MSM_IMPL in the
        environment the dispatcher must resolve to xla, so `make bench-fast`
        gates the path the floors were measured on."""
        from spectre_tpu.ops import msm as MSM
        monkeypatch.delenv("SPECTRE_MSM_IMPL", raising=False)
        assert MSM.msm_impl() == "xla"

"""Serving-gateway drills (ISSUE 14).

Cache-semantics pins (ETag = content digest, stable across restarts;
If-None-Match -> 304; `immutable` only on sealed periods; head-period
short TTL), pack byte-identity against direct UpdateStore reads, pack
survival across restart replay + a scrubber pass, corrupt-pack
quarantine -> rebuild, the `gateway.pack_write` fault drill, counter
parity into /metrics, and the ISSUE-14 acceptance drill: a follower
proves >=3 periods, packs seal, a 10^4-client Zipf load run completes
with zero sealed-period store fallbacks while a fault schedule
(`gateway.pack_write:ioerror` + a torn follower-journal tail) is
active.

Runs in the default tier and via `make test-gateway` / `make
test-faults`.
"""

import json
import os

import pytest

from spectre_tpu.follower.updates import UpdateStore
from spectre_tpu.gateway import (Gateway, GatewayCache, PackBuilder,
                                 canonical_update_body, decode_pack,
                                 encode_pack)
from spectre_tpu.gateway.packs import PACK_MAGIC, PACK_SUFFIX
from spectre_tpu.loadgen import InProcessTarget, ZipfSampler, run_drill
from spectre_tpu.prover_service.scrubber import Scrubber
from spectre_tpu.utils import faults
from spectre_tpu.utils.health import HEALTH, ServiceHealth


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _result(period: int) -> dict:
    return {"proof": "0x" + bytes([period % 251]).hex() * 48,
            "committee_poseidon": hex(period * 7919 + 13),
            "instances": [hex(period), hex(period + 1)]}


def _mk_store(directory, periods, start: int = 5,
              health=HEALTH) -> UpdateStore:
    store = UpdateStore(str(directory), health=health)
    for p in range(start, start + periods):
        store.append_committee(p, _result(p))
    return store


def _store_body(store, period: int) -> bytes:
    """The canonical encoding of a direct UpdateStore read — the bytes
    every gateway path must match exactly."""
    return canonical_update_body(store.get_committee(period))


# -- hot cache ---------------------------------------------------------------


class TestGatewayCache:
    def test_byte_budget_lru_eviction_counted(self):
        h = ServiceHealth()
        c = GatewayCache(cache_mb=10 / (1 << 20), health=h)   # 10 bytes
        assert c.put("a", "A", 4) and c.put("b", "B", 4)
        assert c.get("a") == "A"                 # refresh: a is now MRU
        assert c.put("c", "C", 4)                # evicts b (LRU)
        assert c.get("b") is None
        assert c.get("a") == "A" and c.get("c") == "C"
        assert h.get("gateway_cache_evictions") == 1
        st = c.stats()
        assert st["entries"] == 2 and st["bytes"] == 8
        assert st["hits"] == 3 and st["misses"] == 1

    def test_oversize_entry_refused_not_thrashed(self):
        h = ServiceHealth()
        c = GatewayCache(cache_mb=10 / (1 << 20), health=h)
        c.put("a", "A", 8)
        assert not c.put("big", "B", 64)         # larger than the budget
        assert c.get("a") == "A"                 # hot set untouched
        assert h.get("gateway_cache_evictions") == 0

    def test_invalidate_and_clear(self):
        c = GatewayCache(cache_mb=1)
        c.put("a", "A", 4)
        c.invalidate("a")
        assert c.get("a") is None
        c.put("b", "B", 4)
        c.clear()
        assert c.stats()["entries"] == 0 and c.stats()["bytes"] == 0


# -- pack format -------------------------------------------------------------


class TestPackFormat:
    def test_roundtrip_and_slice_offsets(self):
        entries = [(7, "e7", b'{"p":7}'), (8, "e8", b'{"period":8}')]
        data = encode_pack(7, entries, tail=False)
        assert data.startswith(PACK_MAGIC)
        index, base = decode_pack(data)
        assert index["start"] == 7 and index["count"] == 2
        assert index["tail"] is False
        for ent, (_, etag, body) in zip(index["entries"], entries):
            assert ent["etag"] == etag
            off, ln = base + ent["offset"], ent["length"]
            assert data[off:off + ln] == body

    def test_bad_magic_raises(self):
        with pytest.raises(ValueError, match="magic"):
            decode_pack(b"NOTAPACK" + b"\x00" * 16)


# -- HTTP cache semantics ----------------------------------------------------


class TestServingSemantics:
    def test_etag_is_content_digest_and_stable_across_restart(self,
                                                              tmp_path):
        store = _mk_store(tmp_path, periods=6)
        gw = Gateway(store, pack_periods=4)
        _, hdr, _ = gw.handle_http("/v1/update/6")
        assert hdr["ETag"] == f'"{store.committee_digest(6)}"'
        built = HEALTH.get("gateway_packs_built")
        # restart: fresh store + gateway over the same dir — the ETag is
        # the journaled content digest, so it cannot move; the packs
        # replay from their journal instead of rebuilding
        gw2 = Gateway(UpdateStore(str(tmp_path)), pack_periods=4)
        _, hdr2, _ = gw2.handle_http("/v1/update/6")
        assert hdr2["ETag"] == hdr["ETag"]
        assert HEALTH.get("gateway_packs_built") == built

    def test_304_on_if_none_match(self, tmp_path):
        gw = Gateway(_mk_store(tmp_path, periods=4), pack_periods=2)
        n0 = HEALTH.get("gateway_304s")
        st, hdr, body = gw.handle_http("/v1/update/6")
        assert st == 200
        st2, hdr2, body2 = gw.handle_http(
            "/v1/update/6", {"If-None-Match": hdr["ETag"]})
        assert st2 == 304 and body2 == b""
        assert hdr2["ETag"] == hdr["ETag"]       # revalidation re-pins it
        assert HEALTH.get("gateway_304s") == n0 + 1
        # a stale validator re-downloads
        st3, _, body3 = gw.handle_http(
            "/v1/update/6", {"If-None-Match": '"deadbeef"'})
        assert st3 == 200 and body3 == body

    def test_immutable_only_on_sealed_periods(self, tmp_path):
        store = _mk_store(tmp_path, periods=4)    # periods 5..8, tip 8
        gw = Gateway(store, pack_periods=2, head_ttl_s=7)
        for p in (5, 6, 7):
            _, hdr, _ = gw.handle_http(f"/v1/update/{p}")
            assert "immutable" in hdr["Cache-Control"], p
            assert "max-age=31536000" in hdr["Cache-Control"]
        # the head (tip) period may still change: short TTL, no immutable
        _, hdr, _ = gw.handle_http("/v1/update/8")
        assert hdr["Cache-Control"] == "public, max-age=7"
        # ranges: immutable only when the whole range is sealed
        _, hdr, _ = gw.handle_http("/v1/updates?start=5&count=3")
        assert "immutable" in hdr["Cache-Control"]
        _, hdr, _ = gw.handle_http("/v1/updates?start=7&count=2")
        assert "immutable" not in hdr["Cache-Control"]
        # bootstrap is tip-derived: never immutable
        _, hdr, _ = gw.handle_http("/v1/bootstrap")
        assert "immutable" not in hdr["Cache-Control"]

    def test_single_update_byte_identical_to_store_read(self, tmp_path):
        store = _mk_store(tmp_path, periods=5)
        gw = Gateway(store, pack_periods=2)
        for p in range(5, 10):
            _, _, body = gw.handle_http(f"/v1/update/{p}")
            assert body == _store_body(store, p), p

    def test_range_byte_identical_and_missing(self, tmp_path):
        store = _mk_store(tmp_path, periods=5)    # 5..9
        gw = Gateway(store, pack_periods=2)
        st, _, body = gw.handle_http("/v1/updates?start=4&count=4")
        obj = json.loads(body)
        assert obj["missing"] == [4]
        updates, missing = store.range_committee(4, 4)
        manual = json.dumps({"missing": missing, "updates": updates},
                            sort_keys=True, separators=(",", ":")).encode()
        assert body == manual
        # range etag revalidates
        _, hdr, _ = gw.handle_http("/v1/updates?start=5&count=3")
        st2, _, _ = gw.handle_http("/v1/updates?start=5&count=3",
                                   {"If-None-Match": hdr["ETag"]})
        assert st2 == 304

    def test_bootstrap_document(self, tmp_path):
        store = _mk_store(tmp_path, periods=4)
        gw = Gateway(store, pack_periods=2)
        st, hdr, body = gw.handle_http("/v1/bootstrap")
        assert st == 200
        obj = json.loads(body)
        assert obj["anchor_period"] == 5 and obj["tip_period"] == 8
        assert canonical_update_body(obj["update"]) == \
            _store_body(store, 5)
        st2, _, _ = gw.handle_http("/v1/bootstrap",
                                   {"If-None-Match": hdr["ETag"]})
        assert st2 == 304

    def test_missing_and_malformed_requests(self, tmp_path):
        gw = Gateway(_mk_store(tmp_path, periods=2), pack_periods=2)
        st, hdr, _ = gw.handle_http("/v1/update/99")
        assert st == 404 and hdr["Cache-Control"] == "no-store"
        assert gw.handle_http("/v1/nope")[0] == 404
        assert gw.handle_http("/v1/update/xyz")[0] == 400
        assert gw.handle_http("/v1/updates?count=3")[0] == 400


# -- pack lifecycle ----------------------------------------------------------


class TestPackLifecycle:
    def test_every_sealed_period_is_pack_covered(self, tmp_path):
        """Full packs over aligned ranges + ONE tail pack over the
        sealed remainder: no sealed period is ever left to the store."""
        store = _mk_store(tmp_path, periods=8)    # 5..12, tip 12
        gw = Gateway(store, pack_periods=3)
        fb0 = HEALTH.get("gateway_store_fallbacks")
        for p in range(5, 12):                    # every sealed period
            assert gw.packs.pack_for(p) is not None, p
            st, _, body = gw.handle_http(f"/v1/update/{p}")
            assert st == 200 and body == _store_body(store, p)
        assert HEALTH.get("gateway_store_fallbacks") == fb0

    def test_tail_pack_rebuilt_as_tip_advances(self, tmp_path):
        store = _mk_store(tmp_path, periods=3)    # 5..7
        gw = Gateway(store, pack_periods=4)
        tail0 = gw.packs.pack_for(5)
        assert tail0 is not None and tail0["tail"]
        live0 = gw.live_artifacts()
        store.append_committee(8, _result(8))     # append hook reseals
        tail1 = gw.packs.pack_for(7)
        assert tail1 is not None and tail1["count"] == 3
        # the superseded tail dropped out of the live set (the scrubber
        # reaps it as an orphan — intended lifecycle)
        assert (tail0["digest"], PACK_SUFFIX) not in gw.live_artifacts()
        assert live0 != gw.live_artifacts()

    def test_packs_survive_restart_and_scrubber_pass(self, tmp_path):
        """Restart replays the pack journal (no rebuild), and a scrubber
        pass with the gateway's live set keeps every current pack while
        reaping superseded ones."""
        store = _mk_store(tmp_path, periods=7)    # 5..11
        gw = Gateway(store, pack_periods=4)       # full [5,8] + tail [9,10]
        store.append_committee(12, _result(12))   # tail reseals as [9,11]
        live = store.live_artifacts() | gw.live_artifacts()
        summary = Scrubber(store.store, lambda: live,
                           min_age_s=0.0).scrub()
        assert summary["corrupt"] == 0
        assert summary["expired"] >= 1            # the old tail pack
        for digest, suffix in gw.live_artifacts():
            assert store.store.exists(digest, suffix)
        # restart: replay, not rebuild — and serving stays pack-backed
        built = HEALTH.get("gateway_packs_built")
        fb0 = HEALTH.get("gateway_store_fallbacks")
        gw2 = Gateway(UpdateStore(str(tmp_path)), pack_periods=4)
        assert HEALTH.get("gateway_packs_built") == built
        for p in range(5, 12):
            st, _, body = gw2.handle_http(f"/v1/update/{p}")
            assert st == 200 and body == _store_body(store, p)
        assert HEALTH.get("gateway_store_fallbacks") == fb0

    def test_offline_scrub_cli_keeps_updates_and_packs(self, tmp_path):
        """The `scrub` CLI replays the follower + pack journals into its
        live set: an offline pass over a follower params dir must not
        expire the update chain or its packs (it used to see only the
        job journal)."""
        from spectre_tpu.prover_service.cli import main as cli_main
        store = _mk_store(tmp_path, periods=5)
        Gateway(store, pack_periods=2)
        rc = cli_main(["scrub", "--params-dir", str(tmp_path),
                       "--min-age-s", "0"])
        assert not rc
        for p in range(5, 10):                    # chain fully intact
            assert store.get_committee(p)["period"] == p
        gw2 = Gateway(UpdateStore(str(tmp_path)), pack_periods=2)
        for p in (5, 6, 7, 8):
            assert gw2.packs.pack_for(p) is not None, p

    def test_corrupt_pack_quarantined_then_rebuilt(self, tmp_path):
        store = _mk_store(tmp_path, periods=5)    # 5..9
        gw = Gateway(store, pack_periods=2)
        meta = gw.packs.pack_for(5)
        path = store.store.path_for(meta["digest"], PACK_SUFFIX)
        raw = open(path, "rb").read()
        with open(path, "wb") as f:               # rot on disk
            f.write(raw[:-3] + b"\xff\xff\xff")
        q0 = HEALTH.get("artifacts_quarantined")
        c0 = HEALTH.get("gateway_pack_corrupt")
        st, _, body = gw.handle_http("/v1/update/5")
        assert st == 200 and body == _store_body(store, 5)
        assert HEALTH.get("gateway_pack_corrupt") == c0 + 1
        assert HEALTH.get("artifacts_quarantined") == q0 + 1
        # rotten bytes moved to quarantine/ for post-mortem; the rebuild
        # re-covers the period (same content -> same digest/path, now
        # with verifying bytes)
        qdir = store.store.quarantine_dir
        assert os.path.isdir(qdir) and os.listdir(qdir)
        meta2 = gw.packs.pack_for(5)
        assert meta2 is not None
        assert store.store.exists(meta2["digest"], PACK_SUFFIX)
        assert open(path, "rb").read() == raw     # fresh, verifying

    def test_pack_write_fault_falls_back_then_recovers(self, tmp_path,
                                                       monkeypatch):
        store = _mk_store(tmp_path, periods=5)
        monkeypatch.setenv("SPECTRE_FAULT_PLAN",
                           "gateway.pack_write:ioerror:99")
        bf0 = HEALTH.get("gateway_pack_build_failures")
        fb0 = HEALTH.get("gateway_store_fallbacks")
        gw = Gateway(store, pack_periods=2)       # every build fails
        assert HEALTH.get("gateway_pack_build_failures") > bf0
        st, _, body = gw.handle_http("/v1/update/6")
        assert st == 200 and body == _store_body(store, 6)
        assert HEALTH.get("gateway_store_fallbacks") > fb0   # degraded
        monkeypatch.delenv("SPECTRE_FAULT_PLAN")
        faults.clear()                            # disk recovers
        fb1 = HEALTH.get("gateway_store_fallbacks")
        st, _, body = gw.handle_http("/v1/update/6")
        assert st == 200 and body == _store_body(store, 6)
        assert HEALTH.get("gateway_store_fallbacks") == fb1  # pack again

    def test_torn_pack_journal_tail_tolerated(self, tmp_path):
        store = _mk_store(tmp_path, periods=5)
        gw = Gateway(store, pack_periods=2)
        jpath = gw.packs._journal_path
        with open(jpath, "a") as f:
            f.write('{"start": 5, "digest": "to')          # torn append
        gw2 = Gateway(UpdateStore(str(tmp_path)), pack_periods=2)
        st, _, body = gw2.handle_http("/v1/update/5")
        assert st == 200 and body == _store_body(store, 5)

    def test_hole_below_tip_blocks_that_pack_only(self, tmp_path):
        """An invalidated mid-chain record (being re-proved) keeps ITS
        range unpacked; the other sealed ranges still seal."""
        health = ServiceHealth()
        store = _mk_store(tmp_path, periods=6, health=health)   # 5..10
        del store._committee[6]                    # simulated hole
        pb = PackBuilder(store, pack_periods=2, health=health)
        pb.ensure_packs()
        assert pb.pack_for(6) is None and pb.pack_for(5) is None
        assert pb.pack_for(7) is not None and pb.pack_for(9) is not None


# -- counters ride HEALTH into /metrics --------------------------------------


class TestMetricsExport:
    def test_gateway_counters_and_gauges_in_prom(self, tmp_path):
        from spectre_tpu.observability import prom
        gw = Gateway(_mk_store(tmp_path, periods=4), pack_periods=2)
        gw.handle_http("/v1/update/5")
        _, hdr, _ = gw.handle_http("/v1/update/6")
        gw.handle_http("/v1/update/6", {"If-None-Match": hdr["ETag"]})
        body = prom.render()
        for family in ("spectre_gateway_requests_total",
                       "spectre_gateway_304s_total",
                       "spectre_gateway_pack_hits_total",
                       "spectre_gateway_packs",
                       "spectre_gateway_cache_budget_bytes",
                       "spectre_gateway_request_seconds_bucket"):
            assert family in body, family
        # exporter untouched: the counters ride HEALTH.snapshot()
        snap = HEALTH.snapshot()["counters"]
        assert snap.get("gateway_requests", 0) >= 3
        assert snap.get("gateway_304s", 0) >= 1


# -- load generator ----------------------------------------------------------


class TestLoadgen:
    def test_zipf_sampler_skews_hot(self):
        import random
        z = ZipfSampler(100, s=1.2)
        rng = random.Random(7)
        draws = [z.sample(rng) for _ in range(4000)]
        assert all(0 <= d < 100 for d in draws)
        top = sum(1 for d in draws if d < 10)
        assert top > len(draws) * 0.5           # rank 0-9 dominate

    def test_drill_report_shape_and_304_path(self, tmp_path):
        h = ServiceHealth()
        store = _mk_store(tmp_path, periods=6, health=h)
        gw = Gateway(store, pack_periods=2, health=h)
        rep = run_drill(InProcessTarget(gw),
                        periods=list(range(10, 4, -1)), tip=10,
                        clients=50, requests=1500, seed=3, health=h)
        assert rep["requests"] == 1500
        assert rep["statuses"].get("200", 0) + \
            rep["statuses"].get("304", 0) == 1500
        assert rep["latency_ms"]["p99"] >= rep["latency_ms"]["p50"]
        assert rep["if_none_match_sent"] > 0
        assert rep["statuses"].get("304", 0) > 0
        assert rep["gateway_counters"]["gateway_requests"] == 1500
        assert rep["gateway_counters"].get("gateway_store_fallbacks",
                                           0) == 0


# -- ISSUE 14 acceptance drill -----------------------------------------------


class TestAcceptanceDrill:
    def test_follower_to_loadgen_end_to_end_with_faults(self, tmp_path,
                                                        monkeypatch):
        """Follower proves >=3 periods -> packs seal -> a 10^4-client
        Zipf drill completes with every sealed-period response served
        from the pack/304 paths (ZERO store fallbacks), byte-identical
        to direct UpdateStore reads — with `gateway.pack_write:ioerror`
        armed and a torn follower-journal tail replayed mid-drill."""
        from test_follower import (DOMAIN, TINY, FakeBeacon,
                                   _FollowerState, _drive, _mk_queue,
                                   _step_pubkeys_hex)
        from spectre_tpu.follower import Follower

        state = _FollowerState(TINY)
        jobs = _mk_queue(state, tmp_path)
        beacon = FakeBeacon(TINY, fin_slot=80)
        fol = Follower(TINY, beacon, jobs, directory=str(tmp_path),
                       pubkeys=_step_pubkeys_hex(TINY), domain=DOMAIN)
        # fault 1: the FIRST pack write fails with an ioerror — builds
        # must retry on later seal events, not break the follower
        monkeypatch.setenv("SPECTRE_FAULT_PLAN",
                           "gateway.pack_write:ioerror:1")
        gw = Gateway(fol.store, pack_periods=2, cache_mb=16)
        try:
            for fin_slot in (80, 144, 208, 272):   # periods 1..4
                beacon.advance(fin_slot)
                period = TINY.sync_period(fin_slot)
                _drive(fol, lambda: fol.store.has_committee(period))
        finally:
            jobs.stop()
        assert fol.store.tip_period() == 4        # sealed: 1, 2, 3
        monkeypatch.delenv("SPECTRE_FAULT_PLAN")
        faults.clear()

        # fault 2: torn follower-journal tail (crash mid-append), then
        # restart the read path over the same dir
        with open(fol.store.path, "a") as f:
            f.write('{"kind": "committee", "per')
        store2 = UpdateStore(str(tmp_path))
        assert store2.tip_period() == 4
        gw2 = Gateway(store2, pack_periods=2, cache_mb=16)
        # despite the failed first build, every sealed period is covered
        for p in (1, 2, 3):
            assert gw2.packs.pack_for(p) is not None, p

        fb0 = HEALTH.get("gateway_store_fallbacks")
        rep = run_drill(InProcessTarget(gw2), periods=[4, 3, 2, 1],
                        tip=4, clients=10_000, requests=20_000, seed=14,
                        health=HEALTH)
        # zero store fallbacks for sealed traffic -> every sealed 200
        # came off a pack slice; with the 304s that is 100% >= 95%
        assert HEALTH.get("gateway_store_fallbacks") == fb0
        assert rep["sealed_requests"] > 0
        served_cached = rep["sealed_requests"]    # all pack or 304
        assert served_cached / rep["sealed_requests"] >= 0.95
        assert rep["statuses"].get("304", 0) > 0
        bad = {k: v for k, v in rep["statuses"].items()
               if k not in ("200", "304")}
        assert not bad, bad
        # byte identity against direct store reads, post-drill
        for p in (1, 2, 3, 4):
            _, _, body = gw2.handle_http(f"/v1/update/{p}")
            assert body == _store_body(store2, p), p

"""Device kernels (ops/) vs host oracles, on the CPU backend."""

import hashlib
import secrets

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spectre_tpu.fields import bn254 as bn
from spectre_tpu.ops import ec, field_ops as F, limbs as L, msm as MSM
from spectre_tpu.ops import ntt as NTT, poseidon as POS, sha256 as SHA


def rand_fr(n):
    return [secrets.randbelow(bn.R) for _ in range(n)]


class TestLimbs:
    def test_roundtrip(self):
        vals = [0, 1, bn.R - 1, 2**255 - 1, 12345]
        assert L.limbs16_to_ints(L.ints_to_limbs16(vals)) == vals

    def test_u64_u16_conversion(self):
        vals = rand_fr(8)
        from spectre_tpu.native.host import ints_to_limbs
        u64 = ints_to_limbs(vals)
        u16 = L.u64limbs_to_u16limbs(u64)
        assert L.limbs16_to_ints(u16) == vals
        assert np.array_equal(L.u16limbs_to_u64limbs(u16), u64)


class TestFieldOps:
    def test_mul_add_sub_neg(self):
        ctx = F.fr_ctx()
        a, b = rand_fr(64), rand_fr(64)
        am, bm = jnp.asarray(ctx.encode(a)), jnp.asarray(ctx.encode(b))
        assert ctx.decode(F.mont_mul(ctx, am, bm)) == [x * y % bn.R for x, y in zip(a, b)]
        assert ctx.decode(F.add(ctx, am, bm)) == [(x + y) % bn.R for x, y in zip(a, b)]
        assert ctx.decode(F.sub(ctx, am, bm)) == [(x - y) % bn.R for x, y in zip(a, b)]
        assert ctx.decode(F.neg(ctx, am)) == [(-x) % bn.R for x in a]

    def test_edge_values(self):
        ctx = F.fr_ctx()
        e = [0, 1, bn.R - 1, bn.R - 2]
        em = jnp.asarray(ctx.encode(e))
        assert ctx.decode(F.mont_mul(ctx, em, em)) == [x * x % bn.R for x in e]
        assert ctx.decode(F.neg(ctx, jnp.asarray(ctx.encode([0])))) == [0]

    def test_inv_and_pow(self):
        ctx = F.fr_ctx()
        a = rand_fr(8)
        am = jnp.asarray(ctx.encode(a))
        assert ctx.decode(jax.jit(lambda x: F.inv(ctx, x))(am)) == \
            [pow(x, -1, bn.R) for x in a]
        assert ctx.decode(F.mont_pow(ctx, am, 97)) == [pow(x, 97, bn.R) for x in a]

    def test_fq_ctx(self):
        ctx = F.fq_ctx()
        a, b = [secrets.randbelow(bn.P) for _ in range(8)], [secrets.randbelow(bn.P) for _ in range(8)]
        am, bm = jnp.asarray(ctx.encode(a)), jnp.asarray(ctx.encode(b))
        assert ctx.decode(F.mont_mul(ctx, am, bm)) == [x * y % bn.P for x, y in zip(a, b)]


class TestNTT:
    def test_vs_native_and_roundtrip(self):
        k = 6
        w = bn.fr_root_of_unity(k)
        data = rand_fr(1 << k)
        ctx = F.fr_ctx()
        dm = jnp.asarray(ctx.encode(data))
        got = ctx.decode(jax.jit(lambda a: NTT.ntt(a, w))(dm))
        from spectre_tpu.native import host
        dl = host.ints_to_limbs(data)
        host.fr_ntt(dl, w)
        assert got == host.limbs_to_ints(dl)
        back = ctx.decode(jax.jit(lambda a: NTT.intt(a, w))(jnp.asarray(ctx.encode(got))))
        assert back == data

    def test_coset_roundtrip(self):
        k = 5
        w = bn.fr_root_of_unity(k)
        data = rand_fr(1 << k)
        ctx = F.fr_ctx()
        dm = jnp.asarray(ctx.encode(data))
        got = ctx.decode(jax.jit(
            lambda a: NTT.coset_intt(NTT.coset_ntt(a, w, 5), w, 5))(dm))
        assert got == data

    def test_coset_evaluates_on_coset(self):
        # coset_ntt(a, w, g)[i] should equal poly(g * w^i)
        k = 3
        w = bn.fr_root_of_unity(k)
        g = 7
        coeffs = rand_fr(1 << k)
        ctx = F.fr_ctx()
        got = ctx.decode(NTT.coset_ntt(jnp.asarray(ctx.encode(coeffs)), w, g))
        for i in range(1 << k):
            x = g * pow(w, i, bn.R) % bn.R
            want = sum(c * pow(x, j, bn.R) for j, c in enumerate(coeffs)) % bn.R
            assert got[i] == want


class TestEC:
    def test_complete_add_cases(self):
        g = bn.G1_GEN
        pts_a = [g, bn.g1_curve.mul(g, 5), g, g, None, None]
        pts_b = [g, bn.g1_curve.mul(g, 9), None, bn.g1_curve.neg(g), g, None]
        got = ec.decode_points(jax.jit(ec.padd)(
            ec.encode_points(pts_a), ec.encode_points(pts_b)))
        want = [bn.g1_curve.add(a, b) for a, b in zip(pts_a, pts_b)]
        assert got == [None if w is None else (int(w[0]), int(w[1])) for w in want]

    def test_scalar_mul(self):
        got = ec.decode_points(jax.jit(lambda p: ec.scalar_mul(p, 999))(
            ec.encode_points([bn.G1_GEN])))
        w = bn.g1_curve.mul(bn.G1_GEN, 999)
        assert got == [(int(w[0]), int(w[1]))]


class TestMSM:
    def _run(self, pts, scalars, c=None):
        pp = ec.encode_points(pts)
        ss = jnp.asarray(L.ints_to_limbs16(scalars))
        got = ec.decode_points(MSM.msm(pp, ss, c)[None])[0]
        want = bn.g1_curve.msm(pts, scalars)
        want = None if want is None else (int(want[0]), int(want[1]))
        assert got == want

    def test_random(self):
        n = 64
        g = bn.G1_GEN
        pts = [bn.g1_curve.mul(g, secrets.randbelow(bn.R)) for _ in range(n)]
        pts[3] = None
        scalars = rand_fr(n)
        scalars[5] = 0
        self._run(pts, scalars)

    def test_skewed_scalars(self):
        # all-equal scalars: the adversarial case for padded-bucket designs
        n = 64
        pts = [bn.g1_curve.mul(bn.G1_GEN, k + 1) for k in range(n)]
        self._run(pts, [7] * n)

    def test_all_zero(self):
        pts = [bn.g1_curve.mul(bn.G1_GEN, k + 1) for k in range(8)]
        pp = ec.encode_points(pts)
        ss = jnp.asarray(L.ints_to_limbs16([0] * 8))
        assert ec.decode_points(MSM.msm(pp, ss, 4)[None])[0] is None

    def test_single_point(self):
        self._run([bn.G1_GEN], [secrets.randbelow(bn.R)], c=4)


class TestSHA256:
    def test_vs_hashlib(self):
        msgs = [secrets.token_bytes(100) for _ in range(8)]
        assert SHA.sha256_many(msgs) == [hashlib.sha256(m).digest() for m in msgs]

    def test_padding_boundaries(self):
        for ln in (0, 55, 56, 63, 64, 65):
            m = b"a" * ln
            assert SHA.sha256_many([m])[0] == hashlib.sha256(m).digest()

    def test_hash_pairs(self):
        l = [secrets.token_bytes(32) for _ in range(4)]
        r = [secrets.token_bytes(32) for _ in range(4)]
        lw = jnp.asarray(np.stack([SHA.bytes32_to_words(x) for x in l]))
        rw = jnp.asarray(np.stack([SHA.bytes32_to_words(x) for x in r]))
        got = [SHA.words_to_bytes32(x) for x in np.asarray(SHA.hash_pairs(lw, rw))]
        assert got == [hashlib.sha256(a + b).digest() for a, b in zip(l, r)]


class TestPoseidon:
    def test_native_equals_device(self):
        state = rand_fr(POS.T)
        want = POS.permute_native(state)
        ctx = F.fr_ctx()
        sm = jnp.asarray(ctx.encode(state)).reshape(1, POS.T, 16)
        assert ctx.decode(jax.jit(POS.permute)(sm)) == want

    def test_sponge(self):
        s1 = POS.PoseidonSponge()
        s1.absorb([1, 2, 3])
        h1 = s1.squeeze()
        s2 = POS.PoseidonSponge()
        s2.absorb([1, 2, 3])
        assert s2.squeeze() == h1
        s3 = POS.PoseidonSponge()
        s3.absorb([1, 2, 4])
        assert s3.squeeze() != h1
        assert 0 < h1 < bn.R

    def test_constants_shape(self):
        rc, mds = POS.constants()
        assert len(rc) == (POS.R_F + POS.R_P) * POS.T
        assert len(mds) == POS.T and all(len(row) == POS.T for row in mds)
        # MDS must be invertible (Cauchy construction): det != 0 via rank over Fr
        # cheap sanity: no duplicate rows
        assert len({tuple(r) for r in mds}) == POS.T

    def test_golden_vectors_pinned(self):
        """Pinned outputs of the halo2-base-procedure Grain derivation
        (T=12, RATE=11, R_F=8, R_P=65, SECURE_MDS=0). These are derived
        in-repo (no external oracle available offline — see module note);
        pinning makes ANY drift in the generation procedure loud, and gives
        the cross-check target for when a pse-poseidon oracle is available."""
        rc, mds = POS.constants()
        assert rc[0] == 0x2F8B21C35B9D040439B4A4C99454409736FE5CE816A8150E6E27E30E2C886A9B
        assert rc[-1] == 0x24E539B23BAD276B2DAFB1E5C8F68C7B1E03AE757923A01D3C62233927647CA4
        assert mds[0][0] == 0x1B3C91FF6B67F23544228B250E678D20A3122EF1607685B28AF981E84F6DE352
        sp = POS.PoseidonSponge()
        sp.absorb([1, 2, 3])
        assert sp.squeeze() == 0x1B7F414A1AC0F4662FA50E8BA7BD7ED853D2591C20DF0ED3F4610CCDC9048C9E
        assert POS.permute_native([0] * 12)[0] == \
            0x24DA301E2F781BD5A7CD94470F24A69843EEEF45AE7FAE411482F431567A2A44


class TestMSMBatch:
    def test_matches_single(self):
        n, m = 32, 3
        g = bn.G1_GEN
        pts = [bn.g1_curve.mul(g, k + 1) for k in range(n)]
        pp = ec.encode_points(pts)
        scs = [[(i * 131 + k * 7 + 1) % bn.R for k in range(n)] for i in range(m)]
        batch = jnp.stack([jnp.asarray(L.ints_to_limbs16(sc)) for sc in scs])
        res = MSM.msm_batch(pp, batch, c=4)
        got = ec.decode_points(res)
        for sc, g_pt in zip(scs, got):
            want = bn.g1_curve.msm(pts, sc)
            assert g_pt == (int(want[0]), int(want[1]))


class TestMxuField:
    """MXU int8-limb matmul Montgomery multiply (ops/field_mxu.py): exact
    equality with the CIOS path on random + edge values, both BN254 fields.
    (CPU-JAX executes the same graph the TPU tiles onto the MXU; the
    north-star throughput claim needs a live chip — BASELINE.md records the
    tunnel state.)"""

    def test_matches_cios_fr_fq(self):
        import numpy as np
        from spectre_tpu.ops import field_mxu as M
        rng = np.random.default_rng(7)
        for ctx in (F.fr_ctx(), F.fq_ctx()):
            xs = [int.from_bytes(rng.bytes(32), "little") % ctx.p
                  for _ in range(32)]
            ys = [int.from_bytes(rng.bytes(32), "little") % ctx.p
                  for _ in range(32)]
            xs += [0, 1, ctx.p - 1, ctx.p // 2, 2]
            ys += [ctx.p - 1, 0, ctx.p - 1, 2, ctx.p // 3]
            a, b = ctx.encode_np(xs), ctx.encode_np(ys)
            ref = np.asarray(F._mont_mul_cios(ctx, a, b))
            got = np.asarray(M.mont_mul(ctx, a, b))
            assert np.array_equal(ref, got), ctx.name
            for x, y, z in zip(xs, ys, ctx.decode(got)):
                assert z == x * y % ctx.p

    def test_enable_mxu_dispatch_flag(self):
        # mont_mul dispatches on the module flag at trace time (no global
        # rebinding), so stale `from field_ops import mont_mul` bindings
        # still follow enable_mxu() swaps.
        from spectre_tpu.ops import field_mxu as M
        before = F._USE_MXU
        ctx = F.fr_ctx()
        a, b = ctx.encode([3, 5]), ctx.encode([7, 11])
        routed = []
        real = M.mont_mul

        def spy(c, x, y):
            routed.append(True)
            return real(c, x, y)

        M.mont_mul = spy
        try:
            F.enable_mxu(True)
            got = ctx.decode(F.mont_mul(ctx, a, b))
            assert routed, "enable_mxu(True) did not route through field_mxu"
            assert got == [21, 55]
            F.enable_mxu(False)
            routed.clear()
            got = ctx.decode(F.mont_mul(ctx, a, b))
            assert not routed, "enable_mxu(False) still routes through field_mxu"
            assert got == [21, 55]
        finally:
            M.mont_mul = real
            # restore whatever the process was configured with (e.g. a
            # suite-wide SPECTRE_FIELD_IMPL=mxu run must stay on mxu)
            F.enable_mxu(before)


class TestGrainSecondSource:
    """Independent re-derivation of the Grain LFSR stream (integer-register
    implementation, written from the Poseidon reference generator's spec:
    b_{i+80} = b_{i+62}^b_{i+51}^b_{i+38}^b_{i+23}^b_{i+13}^b_i, 160 warmup
    outputs discarded, von Neumann pair filtering) cross-checked against
    ops.poseidon.GrainLFSR. Catches tap/order/init transcription bugs; true
    pse-poseidon BYTE parity still needs an external oracle (none exists
    offline — ops/poseidon.py header records the caveat)."""

    @staticmethod
    def _grain_int(field_bits, t, r_f, r_p, n_bits_out):
        # init word: 2b field_type=1 | 4b sbox=0 | 12b field_bits | 12b t |
        # 10b r_f | 10b r_p | 30x1  (MSB-first), register bit 79 = b_0
        init = (1 << 78) | (0 << 74) | (field_bits << 62) | (t << 50) \
            | (r_f << 40) | (r_p << 30) | ((1 << 30) - 1)
        state = init  # bit 79-i of `state` is stream bit i
        out = []

        def step():
            nonlocal state
            # taps relative to the oldest bit b_i: 62,51,38,23,13,0
            b = 0
            for tap in (62, 51, 38, 23, 13, 0):
                b ^= (state >> (79 - tap)) & 1
            state = ((state << 1) & ((1 << 80) - 1)) | b
            return b

        for _ in range(160):
            step()
        while len(out) < n_bits_out:
            if step():
                out.append(step())
            else:
                step()
        return out

    def test_streams_match(self):
        from spectre_tpu.ops.poseidon import GrainLFSR
        for (fb, t, rf, rp) in [(254, 12, 8, 65), (254, 3, 8, 57)]:
            g = GrainLFSR(fb, t, rf, rp)
            mine = self._grain_int(fb, t, rf, rp, 600)
            theirs = [g.next_filtered_bit() for _ in range(600)]
            assert mine == theirs, (fb, t, rf, rp)

    def test_first_round_constant_sanity(self):
        # rejection-sampled first constant is a valid Fr element and stable
        # (golden of THIS derivation; flags accidental drift)
        from spectre_tpu.fields import bn254
        from spectre_tpu.ops.poseidon import GrainLFSR
        g = GrainLFSR(254, 12, 8, 65)
        c0 = g.next_field_element(bn254.R, 254)
        assert 0 < c0 < bn254.R
        g2 = GrainLFSR(254, 12, 8, 65)
        assert g2.next_field_element(bn254.R, 254) == c0


class TestField384:
    """BLS12-381 device field (24-limb) + batched G1 decompression."""

    def test_mont_mul_matches_host(self):
        import numpy as np
        from spectre_tpu.fields import bls12_381 as bls
        from spectre_tpu.ops import field384 as F3
        ctx = F3.bls_fq_ctx()
        rng = np.random.default_rng(11)
        xs = [int.from_bytes(rng.bytes(48), "little") % ctx.p for _ in range(16)]
        ys = [int.from_bytes(rng.bytes(48), "little") % ctx.p for _ in range(16)]
        xs += [0, 1, ctx.p - 1]
        ys += [ctx.p - 1, 0, ctx.p - 1]
        a, b = ctx.encode_np(xs), ctx.encode_np(ys)
        got = ctx.decode(np.asarray(F3.mont_mul(ctx, a, b)))
        for x, y, z in zip(xs, ys, got):
            assert z == x * y % ctx.p

    def test_decompress_batch_matches_host(self):
        from spectre_tpu.fields import bls12_381 as bls
        from spectre_tpu.ops.field384 import g1_decompress_batch
        # mix of sign bits (negate half the points)
        pts = []
        for i in range(6):
            p = bls.sk_to_pk(7919 * i + 3)
            if i % 2:
                p = bls.g1_curve.neg(p)
            pts.append(bls.g1_compress(p))
        got = g1_decompress_batch(pts)
        for k, g in zip(pts, got):
            x, y = bls.g1_decompress(k)
            assert (int(x), int(y)) == g

    def test_decompress_rejects_off_curve(self):
        import pytest as _pytest
        from spectre_tpu.fields import bls12_381 as bls
        from spectre_tpu.ops.field384 import g1_decompress_batch
        good = bls.g1_compress(bls.sk_to_pk(5))
        # find an x with no sqrt(x^3+4): x=1 -> 5 is a QR? craft by search
        for cand in range(1, 50):
            if pow((cand ** 3 + 4) % bls.P, (bls.P - 1) // 2, bls.P) != 1:
                bad_x = cand
                break
        bad = bytearray(int(bad_x).to_bytes(48, "big"))
        bad[0] |= 0x80
        with _pytest.raises(AssertionError):
            g1_decompress_batch([good, bytes(bad)])

"""Test configuration: force an 8-device virtual CPU mesh before JAX backend init.

NOTE: this environment pins JAX_PLATFORMS=axon (TPU tunnel) via sitecustomize,
and the env var cannot be overridden from here — jax.config.update CAN. The
XLA_FLAGS host-device count must still be set before backend initialization.

Multi-chip sharding (parallel/) is exercised on virtual CPU devices here; real
TPU runs happen via bench.py / the driver's dryrun_multichip.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# big circuit graphs compile slowly; persist compiled executables across
# runs. One shared policy (dir keyed by host CPU features — foreign AOT
# entries ABORT at load): spectre_tpu.plonk.backend.setup_compile_cache.
from spectre_tpu.plonk.backend import setup_compile_cache  # noqa: E402

setup_compile_cache()

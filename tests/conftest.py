"""Test configuration: force an 8-device virtual CPU mesh before JAX imports.

Multi-chip sharding (parallel/) is exercised on virtual CPU devices here; real
TPU runs happen via bench.py / the driver's dryrun_multichip.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

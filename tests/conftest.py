"""Test configuration: force an 8-device virtual CPU mesh before JAX backend init.

NOTE: this environment pins JAX_PLATFORMS=axon (TPU tunnel) via sitecustomize,
and the env var cannot be overridden from here — jax.config.update CAN. The
XLA_FLAGS host-device count must still be set before backend initialization.

Multi-chip sharding (parallel/) is exercised on virtual CPU devices here; real
TPU runs happen via bench.py / the driver's dryrun_multichip.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# big circuit graphs compile slowly; persist compiled executables across runs
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache_cpu")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

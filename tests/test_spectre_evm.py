"""The Spectre protocol contract as REAL deployed bytecode.

Reference parity: `contract-tests/tests/spectre.rs` deploys Spectre +
MockVerifiers on anvil and drives step/rotate transactions. Here the SAME
generated Spectre.sol source (contracts/sol_gen.py) is compiled to EVM
bytecode by evm/solc_spectre.py and driven through evm/vm.py's World —
constructor, storage, keccak mapping slots, the sha256 precompile, and
real STATICCALLs into deployed verifier contracts, with metered gas."""

import os

import pytest

from spectre_tpu import spec as SP
from spectre_tpu.contracts.sol_gen import gen_spectre_sol
from spectre_tpu.contracts.spectre import SpectreContract, StepInput
from spectre_tpu.evm import vm as V
from spectre_tpu.evm.solc import Asm
from spectre_tpu.evm.solc_spectre import compile_spectre
from spectre_tpu.plonk.transcript import keccak256

TINY = SP.SPECS["tiny"]
BUILD = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))), "build")
STEP_SIG = "step((uint64,uint64,uint64,bytes32,bytes32),bytes)"
ROTATE_SIG = "rotate(uint256,uint256,uint256,uint256,bytes)"


def _sel(sig: str) -> bytes:
    return keccak256(sig.encode())[:4]


def _mock_verifier(result: bool) -> bytes:
    """Init code for a verifier stub returning a constant bool
    (reference: contracts::MockVerifier, `spectre.rs:97-99`)."""
    a = Asm()
    a.push(1 if result else 0)
    a.push(0)
    a.op("MSTORE")
    a.push(32)
    a.push(0)
    a.op("RETURN")
    rt = a.assemble()
    ia = Asm()
    ia.push(len(rt))
    ia.op("DUP1")
    ia.pushl("rt")
    ia.push(0)
    ia.op("CODECOPY")
    ia.push(0)
    ia.op("RETURN")
    ia.label("rt")
    return ia.assemble()[:-1] + rt


def _step_calldata(inp: StepInput, proof: bytes) -> bytes:
    cd = _sel(STEP_SIG)
    cd += inp.attested_slot.to_bytes(32, "big")
    cd += inp.finalized_slot.to_bytes(32, "big")
    cd += inp.participation.to_bytes(32, "big")
    cd += inp.finalized_header_root + inp.execution_payload_root
    cd += (192).to_bytes(32, "big")            # proof head offset
    cd += len(proof).to_bytes(32, "big") + proof
    if len(proof) % 32:
        cd += b"\x00" * (32 - len(proof) % 32)
    return cd


def _rotate_calldata(slot, poseidon, lo, hi, proof: bytes) -> bytes:
    cd = _sel(ROTATE_SIG)
    for v in (slot, poseidon, lo, hi):
        cd += int(v).to_bytes(32, "big")
    cd += (160).to_bytes(32, "big")
    cd += len(proof).to_bytes(32, "big") + proof
    return cd


class _Deployment:
    def __init__(self, period=2, poseidon=0x1234, step_ok=True,
                 rotate_ok=True):
        self.world = V.World()
        step_v, _ = self.world.deploy(_mock_verifier(step_ok))
        rot_v, _ = self.world.deploy(_mock_verifier(rotate_ok))
        src = gen_spectre_sol(TINY)
        runtime, init, self.meta = compile_spectre(src)
        args = b"".join(int(v).to_bytes(32, "big")
                        for v in (period, poseidon, step_v, rot_v))
        self.addr, self.deploy_gas = self.world.deploy(init, args)

    def view(self, sig: str, *words) -> int:
        data = _sel(sig) + b"".join(int(v).to_bytes(32, "big")
                                    for v in words)
        ok, out, _ = self.world.call_view(self.addr, data)
        assert ok, f"{sig} reverted: {V.revert_reason(out)}"
        return int.from_bytes(out, "big")

    def transact(self, calldata: bytes):
        return self.world.transact(self.addr, calldata)


def _step_input(**kw):
    d = dict(attested_slot=2 * TINY.slots_per_period + 5,
             finalized_slot=2 * TINY.slots_per_period + 1,
             participation=2,
             finalized_header_root=b"\xAA" * 32,
             execution_payload_root=b"\xBB" * 32)
    d.update(kw)
    return StepInput(**d)


@pytest.fixture(scope="module")
def dep():
    return _Deployment()


class TestDeployment:
    def test_deploys_within_eip170_and_initializes(self, dep):
        assert dep.meta["runtime_bytes"] <= 24576
        assert dep.view("head()") == 0
        assert dep.view("SLOTS_PER_PERIOD()") == TINY.slots_per_period
        assert dep.view("SYNC_COMMITTEE_SIZE()") == TINY.sync_committee_size
        assert dep.view("syncCommitteePoseidons(uint256)", 2) == 0x1234
        assert dep.view("syncCommitteePoseidons(uint256)", 3) == 0
        assert dep.deploy_gas > 200 * dep.meta["runtime_bytes"]

    def test_compile_deterministic(self):
        src = gen_spectre_sol(TINY)
        r1, i1, _ = compile_spectre(src)
        r2, i2, _ = compile_spectre(src)
        assert r1 == r2 and i1 == i2


class TestStepTransaction:
    """Mirrors `test_contract_initialization_and_first_step`
    (spectre.rs:35-84): deploy with mocks, step, check post-state."""

    def test_first_step_advances_state(self):
        d = _Deployment()
        inp = _step_input()
        ok, out, gas = d.transact(_step_calldata(inp, b"\x11" * 64))
        assert ok, V.revert_reason(out)
        assert 21000 < gas < 200_000
        assert d.view("head()") == inp.finalized_slot
        assert d.view("blockHeaderRoots(uint256)", inp.finalized_slot) \
            == int.from_bytes(inp.finalized_header_root, "big")
        assert d.view("executionPayloadRoots(uint256)", inp.finalized_slot) \
            == int.from_bytes(inp.execution_payload_root, "big")
        # a later step with an older finalized slot must not move head back
        inp2 = _step_input(attested_slot=inp.attested_slot + 1,
                           finalized_slot=inp.finalized_slot - 1)
        ok, out, _ = d.transact(_step_calldata(inp2, b""))
        assert ok
        assert d.view("head()") == inp.finalized_slot

    def test_matches_python_model(self):
        d = _Deployment()
        inp = _step_input()
        ok, _, _ = d.transact(_step_calldata(inp, b""))
        assert ok
        m = SpectreContract(spec=TINY, initial_sync_period=2,
                            initial_committee_poseidon=0x1234)
        m.step(inp, b"")
        assert m.head == d.view("head()")

    def test_commitment_matches_model_bit_for_bit(self, dep):
        inp = _step_input()
        got = dep.view(
            "toPublicInputsCommitment((uint64,uint64,uint64,bytes32,"
            "bytes32))",
            inp.attested_slot, inp.finalized_slot, inp.participation,
            int.from_bytes(inp.finalized_header_root, "big"),
            int.from_bytes(inp.execution_payload_root, "big"))
        assert got == inp.to_public_inputs_commitment()

    def test_rejects_low_participation(self, dep):
        inp = _step_input(participation=1)
        ok, out, _ = dep.transact(_step_calldata(inp, b""))
        assert not ok
        assert V.revert_reason(out) == "insufficient participation"

    def test_rejects_unknown_period(self):
        d = _Deployment(period=0)
        ok, out, _ = d.transact(_step_calldata(_step_input(), b""))
        assert not ok
        assert V.revert_reason(out) == "no committee for period"

    def test_rejecting_verifier_blocks_step(self):
        d = _Deployment(step_ok=False)
        ok, out, _ = d.transact(_step_calldata(_step_input(), b""))
        assert not ok
        assert V.revert_reason(out) == "step proof invalid"

    def test_uint64_abi_range_check(self, dep):
        cd = bytearray(_step_calldata(_step_input(), b""))
        cd[4:36] = (1 << 64).to_bytes(32, "big")   # attestedSlot too wide
        ok, out, _ = dep.transact(bytes(cd))
        assert not ok and V.revert_reason(out) == "abi: uint64"


class TestRotateTransaction:
    def _stepped(self):
        d = _Deployment()
        inp = _step_input()
        ok, _, _ = d.transact(_step_calldata(inp, b""))
        assert ok
        root = inp.finalized_header_root
        lo = int.from_bytes(root[16:], "big")
        hi = int.from_bytes(root[:16], "big")
        return d, inp, lo, hi

    def test_rotate_flow_and_replay_protection(self):
        d, inp, lo, hi = self._stepped()
        ok, out, gas = d.transact(
            _rotate_calldata(inp.finalized_slot, 0x777, lo, hi, b""))
        assert ok, V.revert_reason(out)
        nxt = TINY.sync_period(inp.finalized_slot) + 1
        assert d.view("syncCommitteePoseidons(uint256)", nxt) == 0x777
        # replay
        ok, out, _ = d.transact(
            _rotate_calldata(inp.finalized_slot, 0x888, lo, hi, b""))
        assert not ok and V.revert_reason(out) == "period already rotated"

    def test_rotate_rejects_wrong_header_root(self):
        d, inp, lo, hi = self._stepped()
        ok, out, _ = d.transact(
            _rotate_calldata(inp.finalized_slot, 0x999, lo + 1, hi, b""))
        assert not ok and V.revert_reason(out) == "header root mismatch"

    def test_rotate_rejects_unknown_slot(self):
        d, inp, lo, hi = self._stepped()
        ok, out, _ = d.transact(
            _rotate_calldata(inp.finalized_slot + 1, 0x999, lo, hi, b""))
        assert not ok and V.revert_reason(out) == "unknown finalized header"


class TestVerifierWiring:
    def test_proof_bytes_reach_the_verifier(self):
        """An echo verifier that accepts iff calldata proof is non-empty
        and starts with 0x42 — proves the proof forwarding path (offsets,
        length, CALLDATACOPY) is byte-faithful."""
        a = Asm()
        # proof data offset within verify() calldata: 4+proof_head ->
        # read head at 36, then len at 4+head, first byte after
        a.push(36)
        a.op("CALLDATALOAD")
        a.push(4)
        a.op("ADD", "DUP1", "CALLDATALOAD")   # [lenpos, len]
        a.op("ISZERO")
        a.pushl("fail")
        a.op("JUMPI")
        a.push(32)
        a.op("ADD", "CALLDATALOAD")
        a.push(248)
        a.op("SHR")
        a.push(0x42)
        a.op("EQ", "ISZERO")
        a.pushl("fail")
        a.op("JUMPI")
        a.push(1)
        a.push(0)
        a.op("MSTORE")
        a.push(32)
        a.push(0)
        a.op("RETURN")
        a.label("fail")
        a.push(0)
        a.push(0)
        a.op("MSTORE")
        a.push(32)
        a.push(0)
        a.op("RETURN")
        rt = a.assemble()
        ia = Asm()
        ia.push(len(rt))
        ia.op("DUP1")
        ia.pushl("rt")
        ia.push(0)
        ia.op("CODECOPY")
        ia.push(0)
        ia.op("RETURN")
        ia.label("rt")
        echo_init = ia.assemble()[:-1] + rt

        w = V.World()
        echo, _ = w.deploy(echo_init)
        rot, _ = w.deploy(_mock_verifier(True))
        runtime, init, _ = compile_spectre(gen_spectre_sol(TINY))
        args = b"".join(int(v).to_bytes(32, "big")
                        for v in (2, 0x1234, echo, rot))
        spectre, _ = w.deploy(init, args)
        inp = _step_input()
        ok, out, _ = w.transact(spectre, _step_calldata(inp, b"\x42abc"))
        assert ok, V.revert_reason(out)
        ok, out, _ = w.transact(spectre, _step_calldata(inp, b"\x43abc"))
        assert not ok and V.revert_reason(out) == "step proof invalid"
        ok, out, _ = w.transact(spectre, _step_calldata(inp, b""))
        assert not ok and V.revert_reason(out) == "step proof invalid"


class TestStorageGasRealism:
    def test_second_step_cheaper_than_first(self):
        """First step writes fresh slots (20k each); overwriting later is
        2.9k — the metered storage schedule shows through."""
        d = _Deployment()
        inp = _step_input()
        ok, _, gas1 = d.transact(_step_calldata(inp, b""))
        assert ok
        ok, _, gas2 = d.transact(_step_calldata(inp, b""))
        assert ok
        assert gas2 < gas1 - 30000


class TestFullStackCompressed:
    """THE production on-chain flow, all real, all bytecode: the checked-in
    Testnet-512 compressed step proof -> Spectre.stepCompressed -> real
    STATICCALL into the COMPILED flagship aggregation verifier -> protocol
    state advances. Mirrors what a mainnet relayer transaction does
    (reference: `rpc.rs:114-163` proof gen + the contract step call)."""

    SOL = os.path.join(BUILD, "aggregation_sync_step_testnet_21_verifier.sol")
    PROOF = os.path.join(BUILD, "agg_step_testnet_21_keccak.proof")

    @pytest.fixture(scope="class")
    def stack(self):
        if not (os.path.exists(self.SOL) and os.path.exists(self.PROOF)):
            pytest.skip("flagship artifacts not in build/")
        import json

        from spectre_tpu.evm.solc import compile_verifier
        from spectre_tpu.witness.step import default_sync_step_args as \
            default_step_args
        with open(self.SOL) as f:
            vsrc = f.read()
        with open(self.PROOF, "rb") as f:
            proof = f.read()
        with open(self.PROOF + ".instances.json") as f:
            stmt = [int(v, 16) for v in json.load(f)["instances"]]
        assert len(stmt) == 14                 # 12 acc limbs + [commit, pos]
        spec = SP.SPECS["testnet"]
        args = default_step_args(spec)
        inp = StepInput(
            attested_slot=args.attested_header.slot,
            finalized_slot=args.finalized_header.slot,
            participation=sum(args.participation_bits),
            finalized_header_root=args.finalized_header.hash_tree_root(),
            execution_payload_root=args.execution_payload_root)
        assert inp.to_public_inputs_commitment() == stmt[12], \
            "fixture drift: StepInput does not produce the proof's commitment"

        w = V.World()
        vrt, vinit, vmeta = compile_verifier(vsrc)
        # the measured flagship verifier exceeds EIP-170 (recorded in the
        # flow record); deploy with the limit waived to exercise the flow
        step_v, _ = w.deploy(vinit, enforce_eip170=False)
        rot_v, _ = w.deploy(_mock_verifier(True))
        runtime, init, _ = compile_spectre(gen_spectre_sol(spec))
        period = inp.attested_slot // spec.slots_per_period
        ctor = b"".join(int(v).to_bytes(32, "big")
                        for v in (period, stmt[13], step_v, rot_v))
        spectre, _ = w.deploy(init, ctor)
        return w, spectre, inp, stmt, proof, vmeta

    @staticmethod
    def _calldata(inp: StepInput, acc: list, proof: bytes) -> bytes:
        sig = ("stepCompressed((uint64,uint64,uint64,bytes32,bytes32),"
               "uint256[12],bytes)")
        cd = _sel(sig)
        cd += inp.attested_slot.to_bytes(32, "big")
        cd += inp.finalized_slot.to_bytes(32, "big")
        cd += inp.participation.to_bytes(32, "big")
        cd += inp.finalized_header_root + inp.execution_payload_root
        for v in acc:
            cd += int(v).to_bytes(32, "big")
        cd += (32 * 18).to_bytes(32, "big")    # proof head (5+12+1 words)
        cd += len(proof).to_bytes(32, "big") + proof
        if len(proof) % 32:
            cd += b"\x00" * (32 - len(proof) % 32)
        return cd

    def test_real_proof_advances_chain_state(self, stack):
        w, spectre, inp, stmt, proof, vmeta = stack
        ok, out, gas = w.transact(spectre, self._calldata(
            inp, stmt[:12], proof), gas=100_000_000)
        assert ok, V.revert_reason(out)
        # protocol post-state
        head = int.from_bytes(
            w.call_view(spectre, _sel("head()"))[1], "big")
        assert head == inp.finalized_slot
        # end-to-end gas: protocol + full in-EVM SNARK verification
        assert 1_000_000 < gas < 2_000_000, gas

    def test_tampered_proof_rejected_on_chain(self, stack):
        w, spectre, inp, stmt, proof, vmeta = stack
        bad = bytearray(proof)
        bad[41] ^= 1
        ok, out, _ = w.transact(spectre, self._calldata(
            inp, stmt[:12], bytes(bad)), gas=100_000_000)
        assert not ok
        # the verifier's revert bubbles through the protocol contract
        assert V.revert_reason(out) in ("identity", "eval range",
                                        "ecMul", "ecAdd", "pairing")

    def test_tampered_accumulator_rejected_on_chain(self, stack):
        w, spectre, inp, stmt, proof, vmeta = stack
        acc = list(stmt[:12])
        acc[0] = (acc[0] + 1) % (1 << 88)
        ok, out, _ = w.transact(spectre, self._calldata(
            inp, acc, proof), gas=100_000_000)
        # instances feed the transcript: verifier returns false or the
        # deferred pairing fails -> require reverts in the protocol
        assert not ok
        assert V.revert_reason(out) in ("step proof invalid", "identity")


def _raw_contract(build) -> bytes:
    a = Asm()
    build(a)
    rt = a.assemble()
    ia = Asm()
    ia.push(len(rt))
    ia.op("DUP1")
    ia.pushl("rt")
    ia.push(0)
    ia.op("CODECOPY")
    ia.push(0)
    ia.op("RETURN")
    ia.label("rt")
    return ia.assemble()[:-1] + rt


class TestWorldSemantics:
    def test_revert_rolls_back_storage(self):
        """A frame that SSTOREs then REVERTs must leave no trace (real
        EVM journaling, not just an error flag)."""
        def prog(a):
            a.push(0xDEAD)
            a.push(7)
            a.op("SSTORE")
            a.push(0)
            a.push(0)
            a.op("REVERT")
        w = V.World()
        addr, _ = w.deploy(_raw_contract(prog))
        ok, _, _ = w.transact(addr, b"")
        assert not ok
        assert w.contracts[addr].storage == {}

    def test_dirty_slot_rewrite_costs_warm_price(self):
        """EIP-2200: second write to the same slot in one tx is 100 gas,
        not another 2900/20000."""
        def prog(a):
            for val in (5, 7):
                a.push(val)
                a.push(3)
                a.op("SSTORE")
            a.op("STOP")
        w = V.World()
        addr, _ = w.deploy(_raw_contract(prog))
        ok, _, gas = w.transact(addr, b"")
        assert ok
        # 21000 intrinsic + pushes + cold(2100) + set(20000) + dirty(100)
        exec_gas = gas - 21000
        assert 22000 < exec_gas < 22400, exec_gas
        assert w.contracts[addr].storage == {3: 7}

    def test_clearing_slot_refunds(self):
        """EIP-3529: clearing a slot refunds 4800, capped at used/5."""
        def prog(a):
            a.push(0)
            a.push(11)
            a.op("SSTORE")
            a.op("STOP")
        w = V.World()
        addr, _ = w.deploy(_raw_contract(prog))
        w.contracts[addr].storage[11] = 5
        ok, _, gas_clear = w.transact(addr, b"")
        assert ok
        assert w.contracts[addr].storage.get(11) is None
        # reset(2900+2100 cold) minus refund, floor-capped at used/5
        exec_gas = gas_clear - 21000
        assert exec_gas < 5000 - 800, exec_gas

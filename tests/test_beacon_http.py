"""Live-HTTP round trip for the Beacon REST client (reference parity: the
reference's live-network preprocessor tests, `preprocessor/src/step.rs:160`,
run against a real Lodestar endpoint; zero-egress here, so a local
http.server serves Beacon-API-shaped JSON built from the deterministic
fixtures and the REAL BeaconClient + converters consume it)."""

import dataclasses
import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from spectre_tpu import spec as SP
from spectre_tpu.fields import bls12_381 as bls
from spectre_tpu.preprocessor import (BeaconClient,
                                      rotation_args_from_update,
                                      step_args_from_finality_update)
from spectre_tpu.witness import (default_committee_update_args,
                                 default_sync_step_args)
from spectre_tpu.witness.types import bytes48_root
from spectre_tpu.gadgets.ssz_merkle import verify_merkle_proof_native
from spectre_tpu.witness.rotation import mock_root

TINY = dataclasses.replace(SP.MINIMAL, name="tiny", sync_committee_size=2)


def _hdr_json(h):
    return {"slot": str(h.slot), "proposer_index": str(h.proposer_index),
            "parent_root": "0x" + h.parent_root.hex(),
            "state_root": "0x" + h.state_root.hex(),
            "body_root": "0x" + h.body_root.hex()}


@pytest.fixture(scope="module")
def server():
    sargs = default_sync_step_args(TINY)
    cargs = default_committee_update_args(TINY)

    bits = bytearray((len(sargs.participation_bits) + 7) // 8)
    for i, b in enumerate(sargs.participation_bits):
        if b:
            bits[i // 8] |= 1 << (i % 8)

    finality_update = {
        "attested_header": _hdr_json(sargs.attested_header),
        "finalized_header": _hdr_json(sargs.finalized_header),
        "finality_branch": ["0x" + b.hex() for b in sargs.finality_branch],
        "execution_payload_root": "0x" + sargs.execution_payload_root.hex(),
        "execution_branch": ["0x" + b.hex()
                             for b in sargs.execution_payload_branch],
        "sync_aggregate": {
            "sync_committee_bits": "0x" + bytes(bits).hex(),
            "sync_committee_signature":
                "0x" + sargs.signature_compressed.hex(),
        },
    }
    # the chain serves the container-depth branch; the converter performs
    # the aggregate-pubkey extension ("magic swap")
    agg = bls.g1_compress(bls.sk_to_pk(424242))
    cont_branch = [b"\x11" * 32] * TINY.sync_committee_depth
    state_root = mock_root(
        cargs.committee_pubkeys_root(),
        [bytes48_root(agg)] + cont_branch,
        TINY.sync_committee_pubkeys_root_index)
    hdr = dataclasses.replace(cargs.finalized_header, state_root=state_root)
    committee_update = {
        "finalized_header": _hdr_json(hdr),
        "next_sync_committee": {
            "pubkeys": ["0x" + pk.hex() for pk in cargs.pubkeys_compressed],
            "aggregate_pubkey": "0x" + agg.hex(),
        },
        "next_sync_committee_branch": ["0x" + b.hex() for b in cont_branch],
    }

    routes = {
        "/eth/v1/beacon/light_client/finality_update":
            {"data": finality_update},
        "/eth/v1/beacon/light_client/updates?start_period=7&count=1":
            [{"data": committee_update}],
        "/eth/v1/beacon/blocks/head/root":
            {"data": {"root": "0x" + (b"\xab" * 32).hex()}},
    }

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            body = routes.get(self.path)
            if body is None:
                self.send_response(404)
                self.end_headers()
                return
            data = json.dumps(body).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):   # quiet
            pass

    httpd = HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_port}", sargs, cargs
    httpd.shutdown()


class TestBeaconHttpRoundTrip:
    def test_finality_update_to_step_args(self, server):
        url, sargs, _ = server
        client = BeaconClient(url)
        update = client.finality_update()
        got = step_args_from_finality_update(
            update, [bls.g1_compress((bls.Fq(x), bls.Fq(y)))
                     for x, y in sargs.pubkeys_uncompressed],
            sargs.domain, TINY)
        assert got.signing_root() == sargs.signing_root()
        assert got.participation_bits == sargs.participation_bits
        assert got.pubkeys_uncompressed == sargs.pubkeys_uncompressed

    def test_committee_update_to_rotation_args(self, server):
        url, _, cargs = server
        client = BeaconClient(url)
        update = client.committee_updates(period=7)[0]
        got = rotation_args_from_update(update, TINY)
        assert got.pubkeys_compressed == cargs.pubkeys_compressed
        # branch was extended by the aggregate-pubkey sibling and verifies
        assert len(got.sync_committee_branch) == TINY.sync_committee_depth + 1
        assert verify_merkle_proof_native(
            got.committee_pubkeys_root(), got.sync_committee_branch,
            TINY.sync_committee_pubkeys_root_index,
            got.finalized_header.state_root)

    def test_head_root(self, server):
        url, _, _ = server
        assert BeaconClient(url).head_block_root() == \
            "0x" + (b"\xab" * 32).hex()

"""Follower subsystem drills (ISSUE 10).

A fixture-backed fake beacon synthesizes VALID light-client updates
(mock-rooted branches + real BLS aggregate signatures, the
witness/step.py + witness/rotation.py recipe parameterized by slot and
period) so the follower exercises the real preprocessor verification
path end to end against a canned-proof state.

Pins the acceptance drills: an unbroken verified update chain across
period boundaries, kill-mid-prove crash replay resuming the chain with
byte-identical stored updates, a cache-hit serving path that never
touches the prover, the beacon-outage degrade/recover loop, plus the
corrupt-stored-update and diskfull fault drills.
"""

import json
import threading
import time
import urllib.request

import pytest

from spectre_tpu import spec as SP
from spectre_tpu.fields import bls12_381 as bls
from spectre_tpu.follower import (ChainOrderError, Follower, UpdateStore,
                                  follower_snapshot)
from spectre_tpu.follower.scheduler import ProofScheduler
from spectre_tpu.follower.tracker import CommitteeUpdateDue, HeadTracker
from spectre_tpu.models import CommitteeUpdateCircuit, StepCircuit
from spectre_tpu.prover_service.jobs import JobQueue
from spectre_tpu.prover_service.rpc import run_proof_method
from spectre_tpu.utils import faults
from spectre_tpu.utils.health import HEALTH
from spectre_tpu.witness.rotation import mock_root
from spectre_tpu.witness.types import (BeaconBlockHeader, CommitteeUpdateArgs,
                                       SyncStepArgs)

TINY = SP.TINY            # 2 validators, 64 slots per sync period
STEP_SEED = 1234
DOMAIN = b"\x07" * 32


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _counter(name: str) -> int:
    return HEALTH.snapshot()["counters"].get(name, 0)


# -- fixture beacon ----------------------------------------------------------

def _hdr_dict(h: BeaconBlockHeader) -> dict:
    return {"slot": h.slot, "proposer_index": h.proposer_index,
            "parent_root": "0x" + h.parent_root.hex(),
            "state_root": "0x" + h.state_root.hex(),
            "body_root": "0x" + h.body_root.hex()}


def _step_sks(spec):
    return [STEP_SEED * 7919 + i + 1 for i in range(spec.sync_committee_size)]


def _step_pubkeys_hex(spec):
    return ["0x" + bls.g1_compress(bls.sk_to_pk(sk)).hex()
            for sk in _step_sks(spec)]


def _mk_finality_update(spec, fin_slot: int) -> dict:
    """A valid LightClientFinalityUpdate for `fin_slot`: mock-rooted
    finality/execution branches, really signed by the deterministic
    step committee (witness/step.py parameterized by slot)."""
    sks = _step_sks(spec)
    finalized = BeaconBlockHeader(
        slot=fin_slot, proposer_index=3, parent_root=b"\x33" * 32,
        state_root=b"\x44" * 32, body_root=b"\x00" * 32)
    exec_root = b"\x55" * 32
    exec_branch = [bytes([0xA0 + d]) * 32
                   for d in range(spec.execution_state_root_depth)]
    finalized.body_root = mock_root(exec_root, exec_branch,
                                    spec.execution_state_root_index)
    fin_branch = [bytes([0xB0 + d]) * 32
                  for d in range(spec.finalized_header_depth)]
    attested = BeaconBlockHeader(
        slot=fin_slot + 2, proposer_index=11, parent_root=b"\x66" * 32,
        state_root=mock_root(finalized.hash_tree_root(), fin_branch,
                             spec.finalized_header_index),
        body_root=b"\x77" * 32)
    args = SyncStepArgs(
        pubkeys_uncompressed=[(int(x), int(y)) for x, y in
                              (bls.sk_to_pk(sk) for sk in sks)],
        participation_bits=[1] * spec.sync_committee_size,
        attested_header=attested, finalized_header=finalized,
        finality_branch=fin_branch, execution_payload_root=exec_root,
        execution_payload_branch=exec_branch, domain=DOMAIN)
    msg = bls.hash_to_g2(args.signing_root(), spec.dst)
    sig = bls.aggregate_signatures([bls.g2_curve.mul(msg, sk) for sk in sks])
    return {
        "attested_header": _hdr_dict(attested),
        "finalized_header": _hdr_dict(finalized),
        "finality_branch": ["0x" + b.hex() for b in fin_branch],
        "execution_payload_root": "0x" + exec_root.hex(),
        "execution_branch": ["0x" + b.hex() for b in exec_branch],
        "sync_aggregate": {
            "sync_committee_bits": [1] * spec.sync_committee_size,
            "sync_committee_signature":
                "0x" + bls.g2_compress(sig).hex(),
        },
    }


def _mk_committee_update(spec, period: int) -> dict:
    """A valid committee update for `period` (distinct committee per
    period — witness/rotation.py parameterized by seed). The branch is
    built at pubkeys depth so no aggregate-pubkey extension is needed."""
    seed = 1000 * (period + 1)
    n = spec.sync_committee_size
    pks = [bls.sk_to_pk(seed + i + 1) for i in range(n)]
    pubkeys = [bls.g1_compress(p) for p in pks]
    args = CommitteeUpdateArgs(pubkeys_compressed=pubkeys)
    branch = [bytes([(period + d) % 251]) * 32
              for d in range(spec.sync_committee_pubkeys_depth)]
    state_root = mock_root(args.committee_pubkeys_root(), branch,
                           spec.sync_committee_pubkeys_root_index)
    finalized = BeaconBlockHeader(
        slot=period * spec.slots_per_period + 1, proposer_index=7,
        parent_root=b"\x11" * 32, state_root=state_root,
        body_root=b"\x22" * 32)
    agg = bls.g1_compress(bls.aggregate_pubkeys(pks)) \
        if hasattr(bls, "aggregate_pubkeys") else pubkeys[0]
    return {
        "finalized_header": _hdr_dict(finalized),
        "next_sync_committee": {
            "pubkeys": ["0x" + pk.hex() for pk in pubkeys],
            "aggregate_pubkey": "0x" + agg.hex(),
        },
        "next_sync_committee_branch": ["0x" + b.hex() for b in branch],
    }


class FakeBeacon:
    """Duck-typed BeaconClient: deterministic valid updates, an
    `outage` switch for the degrade drill."""

    def __init__(self, spec, fin_slot: int):
        self.spec = spec
        self.fin_slot = fin_slot
        self.outage = False
        self._fin_cache: dict[int, dict] = {}
        self._com_cache: dict[int, dict] = {}

    def advance(self, fin_slot: int):
        self.fin_slot = fin_slot

    def finality_update(self) -> dict:
        if self.outage:
            raise OSError("beacon down")
        if self.fin_slot not in self._fin_cache:
            self._fin_cache[self.fin_slot] = _mk_finality_update(
                self.spec, self.fin_slot)
        return self._fin_cache[self.fin_slot]

    def committee_updates(self, period: int, count: int = 1) -> list:
        if self.outage:
            raise OSError("beacon down")
        if period not in self._com_cache:
            self._com_cache[period] = _mk_committee_update(self.spec, period)
        return [self._com_cache[period]]


# -- canned-proof state ------------------------------------------------------

class _FollowerState:
    """Canned prover (proving for real is minutes): real get_instances,
    fault-checkable at `backend.prove` for the crash drill, counts every
    prove call so the cache-hit pin can assert the prover was idle."""

    def __init__(self, spec):
        self.spec = spec
        self.concurrency = 1
        self.calls = 0

    def prove_step(self, args):
        faults.check("backend.prove")
        self.calls += 1
        return b"\x01" * 64, StepCircuit.get_instances(args, self.spec)

    def prove_committee(self, args):
        faults.check("backend.prove")
        self.calls += 1
        return (b"\x02" * 64,
                CommitteeUpdateCircuit.get_instances(args, self.spec))


def _mk_queue(state, journal_dir, **kw):
    runner = lambda method, params, heartbeat=None: \
        run_proof_method(state, method, params, heartbeat=heartbeat)
    return JobQueue(runner, concurrency=1, journal_dir=str(journal_dir),
                    stall_timeout=600.0, **kw)


def _drive(follower, predicate, cycles: int = 200, sleep_s: float = 0.02):
    """run_once until `predicate()` (jobs finish on worker threads)."""
    for _ in range(cycles):
        follower.run_once()
        if predicate():
            return
        time.sleep(sleep_s)
    raise AssertionError("follower did not converge")


class _ScriptedJob:
    def __init__(self, jid, result):
        self.id = jid
        self.result = result
        self.manifest_digest = None


class ScriptedJobs:
    """Duck-typed JobQueue whose completions the test scripts by hand —
    the only way to pin out-of-order completion deterministically."""

    def __init__(self):
        self._status: dict[str, str] = {}
        self._results: dict[str, _ScriptedJob] = {}
        self._n = 0

    def submit(self, method, params) -> str:
        self._n += 1
        jid = f"j{self._n}"
        self._status[jid] = "running"
        return jid

    def status(self, jid):
        return {"status": self._status[jid]}

    def result(self, jid):
        return self._results.get(jid)

    def finish(self, jid, result: dict):
        self._status[jid] = "done"
        self._results[jid] = _ScriptedJob(jid, result)


# -- drills ------------------------------------------------------------------

class TestFollowerChain:
    def test_unbroken_chain_across_period_boundaries(self, tmp_path):
        """Acceptance: a beacon advanced across >=2 period boundaries
        yields an unbroken verified update chain + the head step proof;
        the lag gauges return to zero."""
        state = _FollowerState(TINY)
        jobs = _mk_queue(state, tmp_path)
        beacon = FakeBeacon(TINY, fin_slot=80)           # period 1
        fol = Follower(TINY, beacon, jobs, directory=str(tmp_path),
                       pubkeys=_step_pubkeys_hex(TINY), domain=DOMAIN)
        try:
            for fin_slot in (80, 144, 208):              # periods 1, 2, 3
                beacon.advance(fin_slot)
                period = TINY.sync_period(fin_slot)
                _drive(fol, lambda: fol.store.has_committee(period)
                       and fol.store.has_step(fin_slot))
            assert fol.store.tip_period() == 3
            assert sorted(fol.store._committee) == [1, 2, 3]
            assert fol.store.verify_chain()
            # linkage: each record carries its predecessor's poseidon
            for p in (2, 3):
                rec = fol.store.get_committee(p)
                prev = fol.store.get_committee(p - 1)
                assert rec["prev_poseidon"] == \
                    prev["result"]["committee_poseidon"]
            assert fol.tracker.head_lag_slots == 0
            assert fol.tracker.periods_behind == 0
            assert fol.scheduler.backlog == 0
            # provenance linkage: stored records point at their job +
            # manifest (manifest may be None for a journal-less queue,
            # but the job id is always threaded through)
            assert fol.store.get_committee(3)["job_id"]
        finally:
            jobs.stop()

    def test_crash_mid_prove_replay_resumes_chain_byte_identical(
            self, tmp_path):
        """Acceptance: kill mid-prove, journal replay resumes the chain,
        stored updates byte-identical to an uninterrupted run."""
        beacon = FakeBeacon(TINY, fin_slot=80)

        # reference: an uninterrupted run in its own directory
        ref_dir = tmp_path / "ref"
        state_ref = _FollowerState(TINY)
        jobs_ref = _mk_queue(state_ref, ref_dir)
        fol_ref = Follower(TINY, beacon, jobs_ref, directory=str(ref_dir))
        _drive(fol_ref, lambda: fol_ref.store.has_committee(1))
        ref_rec = fol_ref.store._committee[1]
        jobs_ref.stop()

        # crash run: the first prove dies mid-flight (InjectedCrash is a
        # BaseException — the worker thread is killed, the job stays
        # `running` in the journal, exactly a SIGKILL's footprint)
        run_dir = tmp_path / "run"
        state_a = _FollowerState(TINY)
        jobs_a = _mk_queue(state_a, run_dir)
        fol_a = Follower(TINY, beacon, jobs_a, directory=str(run_dir))
        faults.install_plan("backend.prove:crash:1")
        fol_a.run_once()                        # poll + submit
        deadline = time.time() + 5.0
        while faults.fired_count("backend.prove") < 1:
            assert time.time() < deadline, "crash fault never fired"
            time.sleep(0.01)
        time.sleep(0.05)                        # let the worker die
        assert not fol_a.store.has_committee(1)
        jobs_a.stop()

        # restart: replay requeues the running job; a fresh follower on
        # the same directory re-derives the missing period and the
        # witness-digest dedup hands it the SAME job
        state_b = _FollowerState(TINY)
        jobs_b = _mk_queue(state_b, run_dir)
        fol_b = Follower(TINY, beacon, jobs_b, directory=str(run_dir))
        try:
            _drive(fol_b, lambda: fol_b.store.has_committee(1))
            assert fol_b.store.verify_chain()
            rec = fol_b.store._committee[1]
            # content-addressed: digest equality IS byte equality
            assert rec["digest"] == ref_rec["digest"]
            assert rec["committee_poseidon"] == ref_rec["committee_poseidon"]
        finally:
            jobs_b.stop()

    def test_restart_replays_journal_and_serves_without_reproving(
            self, tmp_path):
        """A restarted UpdateStore replays its journal, re-verifies the
        chain tip and serves stored updates without any prover involved."""
        state = _FollowerState(TINY)
        jobs = _mk_queue(state, tmp_path)
        beacon = FakeBeacon(TINY, fin_slot=144)
        fol = Follower(TINY, beacon, jobs, directory=str(tmp_path))
        _drive(fol, lambda: fol.store.has_committee(2))
        calls = state.calls
        jobs.stop()

        store2 = UpdateStore(str(tmp_path))
        assert store2.tip_period() == 2
        assert store2.verify_chain()
        assert store2.get_committee(2)["result"]["committee_poseidon"] \
            == fol.store._committee[2]["committee_poseidon"]
        assert state.calls == calls


class TestFollowerServing:
    def test_cache_hit_never_touches_prover(self, tmp_path):
        """Acceptance pin: getLightClientUpdate for a pre-proved period
        completes without a prove call or a job submission — one
        content-verified artifact read."""
        from spectre_tpu.prover_service.rpc import serve

        state = _FollowerState(TINY)
        jobs = _mk_queue(state, tmp_path)
        state.jobs = jobs               # serve() reuses via ensure_jobs
        store = UpdateStore(str(tmp_path))
        store.append_committee(5, {"proof": "0x02", "instances": ["0x1"],
                                   "committee_poseidon": "0xabc"},
                               job_id="job-5")
        beacon = FakeBeacon(TINY, fin_slot=5 * TINY.slots_per_period)
        fol = Follower(TINY, beacon, jobs, store=store)
        server = serve(state, port=0, background=True, follower=fol)
        port = server.server_address[1]
        try:
            resp = _rpc_post(port, {"jsonrpc": "2.0", "id": 1,
                                    "method": "getLightClientUpdate",
                                    "params": {"period": 5}})
            assert resp["result"]["period"] == 5
            assert resp["result"]["result"]["committee_poseidon"] == "0xabc"
            assert state.calls == 0                 # prover never touched
            assert jobs.stats()["jobs"] == {}       # no job submitted

            rng = _rpc_post(port, {"jsonrpc": "2.0", "id": 2,
                                   "method": "getUpdateRange",
                                   "params": {"start_period": 5,
                                              "count": 3}})
            assert len(rng["result"]["updates"]) == 1
            assert rng["result"]["missing"] == [6, 7]

            st = _rpc_post(port, {"jsonrpc": "2.0", "id": 3,
                                  "method": "followerStatus",
                                  "params": {}})
            assert st["result"]["chain_ok"] is True
            assert st["result"]["tip_period"] == 5

            miss = _rpc_post(port, {"jsonrpc": "2.0", "id": 4,
                                    "method": "getLightClientUpdate",
                                    "params": {"period": 9}})
            assert miss["error"]["code"] == -32007
            assert state.calls == 0
        finally:
            server.shutdown()
            jobs.stop()

    def test_follower_methods_absent_without_follower(self, tmp_path):
        from spectre_tpu.prover_service.rpc import serve

        state = _FollowerState(TINY)
        state.jobs = _mk_queue(state, tmp_path)
        server = serve(state, port=0, background=True)
        port = server.server_address[1]
        try:
            resp = _rpc_post(port, {"jsonrpc": "2.0", "id": 1,
                                    "method": "followerStatus",
                                    "params": {}})
            assert resp["error"]["code"] == -32601
        finally:
            server.shutdown()
            state.jobs.stop()


class TestFollowerFaults:
    def test_beacon_outage_degrades_then_recovers(self, tmp_path):
        """Acceptance: outage flips `degraded` + counts beacon errors,
        in-flight work still pumps; recovery re-derives missed work and
        head_lag returns to 0."""
        state = _FollowerState(TINY)
        jobs = _mk_queue(state, tmp_path)
        beacon = FakeBeacon(TINY, fin_slot=80)
        fol = Follower(TINY, beacon, jobs, directory=str(tmp_path),
                       pubkeys=_step_pubkeys_hex(TINY), domain=DOMAIN)
        try:
            _drive(fol, lambda: fol.store.has_step(80))
            assert fol.tracker.head_lag_slots == 0

            beacon.outage = True
            beacon.advance(144)
            before = _counter("follower_beacon_errors")
            fol.run_once()
            assert fol.degraded is True
            assert _counter("follower_beacon_errors") == before + 1

            beacon.outage = False
            _drive(fol, lambda: fol.store.has_step(144)
                   and fol.store.has_committee(2))
            assert fol.degraded is False
            assert fol.tracker.head_lag_slots == 0
            assert fol.tracker.periods_behind == 0

            # the lag gauges are exported for every live follower
            from spectre_tpu.observability import prom
            text = prom.render()
            assert "spectre_follower_head_lag_slots" in text
            assert "spectre_follower_periods_behind" in text
            assert "spectre_follower_scheduler_backlog" in text
            assert any(f.get("head_lag_slots") == 0
                       for f in follower_snapshot())
        finally:
            jobs.stop()

    def test_corrupt_stored_update_quarantined_and_reproved(self, tmp_path):
        """Acceptance drill: rot under a stored update is caught by the
        content-addressed read, the record is dropped, and the follower
        re-proves the period."""
        state = _FollowerState(TINY)
        jobs = _mk_queue(state, tmp_path)
        beacon = FakeBeacon(TINY, fin_slot=80)
        fol = Follower(TINY, beacon, jobs, directory=str(tmp_path))
        try:
            _drive(fol, lambda: fol.store.has_committee(1))
            before = _counter("follower_updates_invalidated")
            faults.install_plan("artifact.read:corrupt:1")
            assert fol.store.get_committee(1) is None   # dropped + quarantined
            assert _counter("follower_updates_invalidated") == before + 1
            assert not fol.store.has_committee(1)

            _drive(fol, lambda: fol.store.has_committee(1))  # re-proved
            assert fol.store.get_committee(1)["result"]["committee_poseidon"]
            assert fol.store.verify_chain()
        finally:
            jobs.stop()

    def test_diskfull_on_update_store_retries_next_cycle(self, tmp_path):
        """Acceptance drill: ENOSPC under the chain journal counts on
        follower_store_write_failures and the append retries (the job
        result is still journaled — nothing is lost)."""
        clk = {"t": 0.0}
        state = _FollowerState(TINY)
        jobs = _mk_queue(state, tmp_path)
        beacon = FakeBeacon(TINY, fin_slot=80)
        fol = Follower(TINY, beacon, jobs, directory=str(tmp_path),
                       clock=lambda: clk["t"])
        try:
            faults.install_plan("follower.journal:diskfull:1")
            before = _counter("follower_store_write_failures")

            def _failed_once():
                return _counter("follower_store_write_failures") == before + 1

            _drive(fol, _failed_once)
            assert not fol.store.has_committee(1)

            clk["t"] += 120.0          # past the retry backoff
            _drive(fol, lambda: fol.store.has_committee(1))
            assert fol.store.verify_chain()
            assert fol.store.get_committee(1) is not None
        finally:
            jobs.stop()

    def test_scheduler_honors_overload_retry_after(self):
        """A -32001 shed backs the item off by the server's own
        retry_after_s hint instead of hammering the queue."""
        from spectre_tpu.prover_service.jobs import ServiceOverloaded
        from spectre_tpu.follower.tracker import CommitteeUpdateDue

        clk = {"t": 0.0}
        submitted = []

        class SheddingJobs:
            def __init__(self):
                self.shed_left = 2

            def submit(self, method, params):
                if self.shed_left > 0:
                    self.shed_left -= 1
                    raise ServiceOverloaded("queue full", 7.5)
                submitted.append(method)
                return "jid-1"

            def status(self, jid):
                return {"status": "running"}

        class EmptyStore:
            def has_committee(self, p):
                return False

            def has_step(self, s):
                return False

        sched = ProofScheduler(SheddingJobs(), EmptyStore(),
                               clock=lambda: clk["t"])
        sched.offer([CommitteeUpdateDue(1, {"light_client_update": {}})])
        before = _counter("follower_submits_shed")
        summary = sched.pump()
        assert summary["shed"] == 1 and not submitted
        assert _counter("follower_submits_shed") == before + 1
        sched.pump()                       # still inside the backoff window
        assert not submitted
        clk["t"] = 7.6
        sched.pump()                       # second shed, re-priced backoff
        assert not submitted
        clk["t"] = 16.0
        sched.pump()
        assert submitted == ["genEvmProof_CommitteeUpdateCompressed"]
        assert sched.backlog == 1          # in flight until collected


class TestChainOrder:
    """Out-of-order completion must never break the committee chain
    (REVIEW: a backfill whose period-5 job failed transiently while 6
    finished first used to journal 6 with prev_poseidon=None — and
    nothing ever healed it)."""

    def test_out_of_order_completion_holds_until_predecessor_stored(
            self, tmp_path):
        jobs = ScriptedJobs()
        store = UpdateStore(str(tmp_path))
        sched = ProofScheduler(jobs, store, clock=lambda: 0.0)
        sched.offer([
            CommitteeUpdateDue(5, {"light_client_update": {"p": 5}}),
            CommitteeUpdateDue(6, {"light_client_update": {"p": 6}}),
        ])
        sched.pump()                    # j1 <- period 5, j2 <- period 6
        before = _counter("follower_chain_waits")
        jobs.finish("j2", {"committee_poseidon": "0xb"})    # 6 lands first
        sched.pump()
        assert not store.has_committee(6)       # held, NOT stored with a
        assert store.verify_chain()             # dangling None link
        assert _counter("follower_chain_waits") == before + 1
        jobs.finish("j1", {"committee_poseidon": "0xa"})
        summary = sched.pump()          # period order: 5 lands, then 6
        assert summary["stored"] == 2
        assert store._committee[6]["prev_poseidon"] == "0xa"
        assert store.verify_chain()
        assert sched.backlog == 0

    def test_append_committee_rejects_gap_allows_anchor_reprove(
            self, tmp_path):
        store = UpdateStore(str(tmp_path))
        store.append_committee(3, {"committee_poseidon": "0xa"})
        with pytest.raises(ChainOrderError):
            store.append_committee(5, {"committee_poseidon": "0xc"})
        store.append_committee(4, {"committee_poseidon": "0xb"})
        store.append_committee(5, {"committee_poseidon": "0xc"})
        assert store.verify_chain()
        # the trust anchor may legitimately be re-appended with no
        # predecessor after a read-time invalidation
        faults.install_plan("artifact.read:corrupt:1")
        assert store.get_committee(3) is None
        assert store.anchor_period() == 3       # the anchor never moves
        store.append_committee(3, {"committee_poseidon": "0xa"})
        assert sorted(store._committee) == [3, 4, 5]
        assert store.verify_chain()

    def test_hole_below_tip_reemitted_by_tracker(self, tmp_path):
        """REVIEW: missing periods derive from the chain anchor, not
        tip+1 — a quarantined mid-chain record is re-emitted even
        though periods above it are stored."""
        store = UpdateStore(str(tmp_path))
        for p, pos in ((1, "0xa"), (2, "0xb"), (3, "0xc")):
            store.append_committee(p, {"committee_poseidon": pos})
        beacon = FakeBeacon(TINY, fin_slot=3 * TINY.slots_per_period + 16)
        tr = HeadTracker(beacon, TINY, store)
        assert tr.poll() == []                  # chain complete: no work
        faults.install_plan("artifact.read:corrupt:1")
        assert store.get_committee(2) is None   # mid-chain invalidation
        assert store.tip_period() == 3
        items = tr.poll()
        assert [i.period for i in items] == [2]  # hole BELOW the tip
        store.append_committee(2, {"committee_poseidon": "0xb"})
        assert store.verify_chain()
        assert tr.poll() == []

    def test_store_retry_backoff_honored_on_collect_path(self, tmp_path):
        """REVIEW: the keep_job backoff after a store-write OSError must
        actually delay the next append attempt — pump cycles inside the
        window skip the entry instead of hammering a full disk."""
        clk = {"t": 0.0}
        attempts = {"n": 0}

        class FullDiskStore(UpdateStore):
            def append_committee(self, *a, **kw):
                attempts["n"] += 1
                raise OSError("No space left on device")

        jobs = ScriptedJobs()
        sched = ProofScheduler(jobs, FullDiskStore(str(tmp_path)),
                               clock=lambda: clk["t"])
        sched.offer([CommitteeUpdateDue(1, {"light_client_update": {}})])
        sched.pump()
        jobs.finish("j1", {"committee_poseidon": "0xa"})
        sched.pump()
        assert attempts["n"] == 1
        sched.pump()                    # inside the 1 s backoff window
        sched.pump()
        assert attempts["n"] == 1       # backoff honored, no hammering
        clk["t"] = 1.5                  # past the window
        sched.pump()
        assert attempts["n"] == 2

    def test_replay_skips_corrupt_midline_keeps_tail(self, tmp_path):
        """REVIEW: a corrupt journal line mid-file (bit rot) is skipped
        and counted; only a torn LAST line truncates the replay."""
        store = UpdateStore(str(tmp_path))
        store.append_committee(1, {"committee_poseidon": "0xa"})
        store.append_committee(2, {"committee_poseidon": "0xb"})
        with open(store.path) as f:
            lines = f.read().splitlines()
        lines.insert(1, '{"kind": "committe')        # rot mid-file
        with open(store.path, "w") as f:
            f.write("\n".join(lines) + "\n")
        before = _counter("follower_journal_corrupt_lines")
        store2 = UpdateStore(str(tmp_path))
        assert sorted(store2._committee) == [1, 2]   # tail survived
        assert _counter("follower_journal_corrupt_lines") == before + 1
        assert store2.verify_chain()

        # a torn last line is still a tolerated crash footprint
        with open(store.path, "a") as f:
            f.write('{"kind": "step", "slot"')
        b2 = _counter("follower_journal_corrupt_lines")
        store3 = UpdateStore(str(tmp_path))
        assert sorted(store3._committee) == [1, 2]
        # the mid-file rot still counts (+1); the torn tail adds nothing
        assert _counter("follower_journal_corrupt_lines") == b2 + 1


class TestTracker:
    def test_backfill_bounded_per_poll(self, tmp_path):
        """A tracker far behind queues at most SPECTRE_FOLLOW_BACKFILL
        committee periods per poll and counts the deferral."""
        store = UpdateStore(str(tmp_path))
        beacon = FakeBeacon(TINY, fin_slot=6 * TINY.slots_per_period)
        tr = HeadTracker(beacon, TINY, store, backfill=2)
        before = _counter("follower_backfill_deferred")
        items = tr.poll()
        assert [i.period for i in items] == [6]  # anchored at first-seen
        # a store with an old tip is genuinely behind: periods 1..6 due
        store.append_committee(0, {"committee_poseidon": "0x0"})
        items = tr.poll()
        assert [i.period for i in items] == [1, 2]
        assert _counter("follower_backfill_deferred") == before + 1
        assert tr.periods_behind == 6

    def test_steps_disabled_without_domain_and_pubkeys(self, tmp_path):
        store = UpdateStore(str(tmp_path))
        beacon = FakeBeacon(TINY, fin_slot=80)
        tr = HeadTracker(beacon, TINY, store)
        assert not tr.steps_enabled
        items = tr.poll()
        assert all(i.key()[0] == "committee" for i in items)


# -- aggregation cadence (ISSUE 18) ------------------------------------------

@pytest.fixture(scope="module")
def evm_agg_setup():
    """A real tiny-circuit proof + its generated Solidity verifier (the
    test_evm.py recipe): the canned committee prover serves THIS proof,
    so the published aggregate's bytes genuinely verify on-EVM."""
    from test_plonk import _tiny_circuit

    from spectre_tpu.evm import gen_evm_verifier
    from spectre_tpu.plonk.constraint_system import (Assignment,
                                                     CircuitConfig)
    from spectre_tpu.plonk.keygen import keygen
    from spectre_tpu.plonk.prover import prove
    from spectre_tpu.plonk.srs import SRS
    from spectre_tpu.plonk.transcript import KeccakTranscript

    srs = SRS.unsafe_setup(7)
    cfg = CircuitConfig(k=7, num_advice=1, num_lookup_advice=1,
                        num_fixed=1, lookup_bits=4)
    advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
    pk = keygen(srs, cfg, fixed, selectors, copies)
    asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
    proof = prove(pk, srs, asg, transcript=KeccakTranscript())
    src = gen_evm_verifier(pk.vk, srs, num_instances=1)
    return out, proof, src


class _EvmAggState(_FollowerState):
    """Canned prover whose committee proofs are a REAL plonk proof of
    the tiny circuit — every stored period carries EVM-verifiable bytes
    (the poseidon chain still links: one circuit, one instance)."""

    def __init__(self, spec, proof: bytes, out: int):
        super().__init__(spec)
        self._proof, self._out = proof, out

    def prove_committee(self, args):
        faults.check("backend.prove")
        self.calls += 1
        return self._proof, [self._out]


class _CountingVerifier:
    """Delegating verifier wrapper: pins that the EVM simulator really
    ran once per publish (not short-circuited by a mock)."""

    def __init__(self, inner):
        self.inner = inner
        self.calls = 0

    def verify(self, instances, proof) -> bool:
        self.calls += 1
        return self.inner.verify(instances, proof)


class TestAggregationCadence:
    def test_cadence_publishes_evm_verified_windows(self, tmp_path,
                                                    evm_agg_setup):
        """ISSUE 18 acceptance: a follower driven across 2x the cadence
        (5 periods, cadence 2) submits the aggregation circuit over the
        stored chain at each sealed boundary and publishes through the
        contract surface gated by the GENERATED Solidity verifier in
        evm.simulator — calldata included."""
        from spectre_tpu.contracts.spectre import (EvmProofVerifier,
                                                   SpectreContract)
        from spectre_tpu.evm.simulator import run_verifier
        from spectre_tpu.follower.scheduler import AggregationPublisher
        from spectre_tpu.prover_service.calldata import decode_calldata

        out, proof, src = evm_agg_setup
        verifier = _CountingVerifier(EvmProofVerifier(src))
        contract = SpectreContract(TINY, 0, 0, agg_verifier=verifier)
        state = _EvmAggState(TINY, proof, out)
        jobs = _mk_queue(state, tmp_path)
        beacon = FakeBeacon(TINY, fin_slot=80)
        windows_before = _counter("follower_cadence_windows")
        published_before = _counter("follower_aggregations_published")
        fol = Follower(TINY, beacon, jobs, directory=str(tmp_path),
                       cadence_periods=2,
                       publisher=AggregationPublisher(contract))
        try:
            assert fol.snapshot()["agg_cadence_periods"] == 2
            for fin_slot in (80, 144, 208, 272, 336):    # periods 1..5
                beacon.advance(fin_slot)
                period = TINY.sync_period(fin_slot)
                _drive(fol, lambda: fol.store.has_committee(period))
            # boundaries seal strictly below the tip: p=2 and p=4
            _drive(fol, lambda: fol.store.has_aggregate(2)
                   and fol.store.has_aggregate(4))
            assert fol.store.latest_aggregate_period() == 4
            assert not fol.store.has_aggregate(5)        # tip not sealed
            assert sorted(contract.aggregated_ranges) == [2, 4]
            assert verifier.calls == 2                   # EVM sim ran twice
            for end, start in ((2, 1), (4, 3)):
                pub = contract.aggregated_ranges[end]
                assert pub["start_period"] == start
                # the published calldata decodes to exactly the
                # instances + proof the simulator accepted
                blob = bytes.fromhex(pub["calldata"].removeprefix("0x"))
                inst, prf = decode_calldata(blob, 1)
                assert inst == [out] and prf == proof
                rec = fol.store.get_aggregate(end)
                assert rec["start_period"] == start
                assert rec["result"]["committee_poseidon"] == hex(out)
                assert rec["result"]["aggregated"] == 2
                assert rec["job_id"]
            # acceptance, stated literally: the published calldata
            # verifies in evm.simulator
            inst, prf = decode_calldata(bytes.fromhex(
                contract.aggregated_ranges[4]["calldata"]
                .removeprefix("0x")), 1)
            assert run_verifier(src, inst, prf)
            assert _counter("follower_cadence_windows") == \
                windows_before + 2
            assert _counter("follower_aggregations_published") == \
                published_before + 2
            assert fol.store.snapshot()["latest_aggregate_period"] == 4
        finally:
            jobs.stop()

    def test_cadence_restart_rederives_only_unpublished_windows(
            self, tmp_path):
        """has_aggregate() is the dedup key and it SURVIVES restart: a
        follower rebuilt over the same journal never re-submits (or
        re-publishes) a window that already landed."""
        from spectre_tpu.contracts.spectre import SpectreContract
        from spectre_tpu.follower.scheduler import AggregationPublisher

        beacon = FakeBeacon(TINY, fin_slot=80)

        state_a = _FollowerState(TINY)
        jobs_a = _mk_queue(state_a, tmp_path)
        contract_a = SpectreContract(TINY, 0, 0)
        fol_a = Follower(TINY, beacon, jobs_a, directory=str(tmp_path),
                         cadence_periods=2,
                         publisher=AggregationPublisher(contract_a))
        for fin_slot in (80, 144, 208):                  # periods 1..3
            beacon.advance(fin_slot)
            period = TINY.sync_period(fin_slot)
            _drive(fol_a, lambda: fol_a.store.has_committee(period))
        _drive(fol_a, lambda: fol_a.store.has_aggregate(2))
        assert sorted(contract_a.aggregated_ranges) == [2]
        jobs_a.stop()

        # replayed store already knows window 2 is done
        store_b = UpdateStore(str(tmp_path))
        assert store_b.has_aggregate(2)
        assert store_b.latest_aggregate_period() == 2

        windows_before = _counter("follower_cadence_windows")
        state_b = _FollowerState(TINY)
        jobs_b = _mk_queue(state_b, tmp_path)
        contract_b = SpectreContract(TINY, 0, 0)
        fol_b = Follower(TINY, beacon, jobs_b, store=store_b,
                         cadence_periods=2,
                         publisher=AggregationPublisher(contract_b))
        try:
            for fin_slot in (272, 336):                  # periods 4, 5
                beacon.advance(fin_slot)
                period = TINY.sync_period(fin_slot)
                _drive(fol_b, lambda: fol_b.store.has_committee(period))
            _drive(fol_b, lambda: fol_b.store.has_aggregate(4))
            # only the NEW window was derived; window 2 never re-ran
            assert _counter("follower_cadence_windows") == \
                windows_before + 1
            assert sorted(contract_b.aggregated_ranges) == [4]
        finally:
            jobs_b.stop()

    def test_publish_failure_keeps_job_and_retries(self, tmp_path):
        """A publish rejection (simulator refusal, transport break) must
        not lose the finished proof: the job is kept, the failure
        counted, and the SAME job re-publishes after the backoff — no
        re-prove, no resubmission."""
        from spectre_tpu.follower.scheduler import AggregationPublisher

        clk = {"t": 0.0}
        store = UpdateStore(str(tmp_path))
        for p, pos in ((1, "0xa"), (2, "0xb"), (3, "0xc")):
            store.append_committee(p, {"committee_poseidon": pos,
                                       "proof": "0x" + "02" * 64,
                                       "instances": [pos]})

        class FlakyContract:
            def __init__(self):
                self.fails = 1
                self.published = []

            def publish_aggregate(self, **kw):
                if self.fails:
                    self.fails -= 1
                    raise AssertionError("simulator rejected calldata")
                self.published.append(kw)
                return kw

        contract = FlakyContract()
        jobs = ScriptedJobs()
        sched = ProofScheduler(jobs, store, clock=lambda: clk["t"],
                               cadence_periods=2,
                               publisher=AggregationPublisher(contract))
        sched.pump()                        # derives [1,2] -> submits j1
        assert jobs._n == 1
        jobs.finish("j1", {"proof": "0x" + "02" * 64, "instances": ["0xb"],
                           "committee_poseidon": "0xb",
                           "start_period": 1, "period": 2})
        before = _counter("follower_publish_failures")
        sched.pump()                        # publish refused
        assert _counter("follower_publish_failures") == before + 1
        assert not store.has_aggregate(2)   # never journaled unpublished
        assert not contract.published
        sched.pump()                        # inside the backoff window
        assert not contract.published
        clk["t"] = 2.0                      # past the 1 s backoff
        sched.pump()
        assert store.has_aggregate(2)
        assert len(contract.published) == 1
        assert contract.published[0]["period"] == 2
        assert jobs._n == 1                 # same job: no re-prove
        assert sched.backlog == 0

    def test_cadence_window_hole_skipped_until_chain_heals(self, tmp_path):
        """A quarantined mid-window record makes the window underfull:
        it is counted (follower_cadence_holes), skipped this cycle, and
        re-derived once the chain heals — never submitted with a gap."""
        store = UpdateStore(str(tmp_path))
        for p, pos in ((1, "0xa"), (2, "0xb"), (3, "0xc")):
            store.append_committee(p, {"committee_poseidon": pos})
        jobs = ScriptedJobs()
        sched = ProofScheduler(jobs, store, clock=lambda: 0.0,
                               cadence_periods=2)
        holes_before = _counter("follower_cadence_holes")
        faults.install_plan("artifact.read:corrupt:1")
        sched.pump()                        # window read hits the rot
        assert _counter("follower_cadence_holes") == holes_before + 1
        assert jobs._n == 0                 # nothing submitted with a gap
        store.append_committee(1, {"committee_poseidon": "0xa"})  # heal
        sched.pump()
        assert jobs._n == 1                 # window re-derived intact

    def test_agg_method_rejects_broken_chain(self):
        """The aggregation circuit re-checks every poseidon link: a
        tampered window is refused as witness-rejected (AssertionError
        -> -32000), which the dispatcher never fails over."""
        from spectre_tpu.prover_service.rpc import RPC_METHOD_AGG

        state = _FollowerState(TINY)
        good = [{"period": 1, "prev_poseidon": None,
                 "committee_poseidon": "0xa", "proof": "0x01",
                 "instances": ["0x1"]},
                {"period": 2, "prev_poseidon": "0xa",
                 "committee_poseidon": "0xb", "proof": "0x02",
                 "instances": ["0x2"]}]
        res = run_proof_method(state, RPC_METHOD_AGG,
                               {"start_period": 1, "period": 2,
                                "chain": good})
        assert res["aggregated"] == 2
        assert res["committee_poseidon"] == "0xb"
        assert state.calls == 0             # aggregation never re-proves

        broken = [dict(good[0]), dict(good[1], prev_poseidon="0xbad")]
        with pytest.raises(AssertionError, match="chain link broken"):
            run_proof_method(state, RPC_METHOD_AGG,
                             {"start_period": 1, "period": 2,
                              "chain": broken})
        gap = [dict(good[0]), dict(good[1], period=3)]
        with pytest.raises(AssertionError, match="not contiguous"):
            run_proof_method(state, RPC_METHOD_AGG,
                             {"start_period": 1, "period": 3,
                              "chain": gap})


def _rpc_post(port, payload, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/rpc", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)

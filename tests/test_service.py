"""Service layer: preprocessor converters, RPC plumbing, contracts, fixtures."""

import dataclasses
import json
import threading
import urllib.request

import pytest

from spectre_tpu import spec as SP
from spectre_tpu.contracts import MockVerifier, SpectreContract
from spectre_tpu.contracts.spectre import StepInput
from spectre_tpu.models import CommitteeUpdateCircuit, StepCircuit
from spectre_tpu.preprocessor.rotation import rotation_args_from_update
from spectre_tpu.preprocessor.step import step_args_from_finality_update
from spectre_tpu.prover_service.calldata import decode_calldata, encode_calldata
from spectre_tpu.witness import (
    default_committee_update_args,
    default_sync_step_args,
)
from spectre_tpu.test_utils import (
    dump_rotation_fixture,
    dump_step_fixture,
    load_rotation_fixture,
    load_step_fixture,
)

TINY = dataclasses.replace(SP.MINIMAL, name="tiny", sync_committee_size=2)


def _hdr_dict(h):
    return {"slot": h.slot, "proposer_index": h.proposer_index,
            "parent_root": "0x" + h.parent_root.hex(),
            "state_root": "0x" + h.state_root.hex(),
            "body_root": "0x" + h.body_root.hex()}


class TestPreprocessor:
    def test_step_converter_roundtrip(self):
        from spectre_tpu.fields import bls12_381 as bls
        args = default_sync_step_args(TINY)
        pks = [bls.g1_compress((bls.Fq(x), bls.Fq(y)))
               for x, y in args.pubkeys_uncompressed]
        update = {
            "attested_header": _hdr_dict(args.attested_header),
            "finalized_header": _hdr_dict(args.finalized_header),
            "finality_branch": ["0x" + b.hex() for b in args.finality_branch],
            "execution_payload_root": "0x" + args.execution_payload_root.hex(),
            "execution_branch": ["0x" + b.hex() for b in args.execution_payload_branch],
            "sync_aggregate": {
                "sync_committee_bits": args.participation_bits,
                "sync_committee_signature": "0x" + args.signature_compressed.hex(),
            },
        }
        rebuilt = step_args_from_finality_update(
            update, pks, args.domain, TINY)
        assert rebuilt.signing_root() == args.signing_root()
        assert StepCircuit.get_instances(rebuilt, TINY) == \
            StepCircuit.get_instances(args, TINY)

    def test_step_converter_rejects_bad_branch(self):
        args = default_sync_step_args(TINY)
        update = {
            "attested_header": _hdr_dict(args.attested_header),
            "finalized_header": _hdr_dict(args.finalized_header),
            "finality_branch": ["0x" + b"\x00".hex() * 32
                                for _ in args.finality_branch],
            "execution_payload_root": "0x" + args.execution_payload_root.hex(),
            "execution_branch": ["0x" + b.hex() for b in args.execution_payload_branch],
            "sync_aggregate": {"sync_committee_bits": args.participation_bits,
                               "sync_committee_signature": "0x" + args.signature_compressed.hex()},
        }
        with pytest.raises(AssertionError, match="finality branch"):
            step_args_from_finality_update(update, [], args.domain, TINY)

    def test_rotation_converter_with_branch_extension(self):
        from spectre_tpu.fields import bls12_381 as bls
        from spectre_tpu.witness.types import bytes48_root
        from spectre_tpu.gadgets.ssz_merkle import sha256_pair_native
        from spectre_tpu.witness.rotation import mock_root
        args = default_committee_update_args(TINY)
        # craft an update whose branch is the container-depth branch: the
        # converter must extend it with the aggregate-pubkey sibling
        agg = bls.g1_compress(bls.sk_to_pk(999))
        full_branch = [bytes48_root(agg)] + [bytes([d]) * 32
                                             for d in range(TINY.sync_committee_depth)]
        state_root = mock_root(args.committee_pubkeys_root(), full_branch,
                               TINY.sync_committee_pubkeys_root_index)
        hdr = dataclasses.replace(args.finalized_header, state_root=state_root)
        update = {
            "finalized_header": _hdr_dict(hdr),
            "next_sync_committee": {
                "pubkeys": ["0x" + pk.hex() for pk in args.pubkeys_compressed],
                "aggregate_pubkey": "0x" + agg.hex(),
            },
            "next_sync_committee_branch": ["0x" + b.hex() for b in full_branch[1:]],
        }
        rebuilt = rotation_args_from_update(update, TINY)
        assert len(rebuilt.sync_committee_branch) == TINY.sync_committee_pubkeys_depth


class TestCalldata:
    def test_roundtrip(self):
        inst = [123, 456]
        proof = b"\xAB" * 100
        data = encode_calldata(inst, proof)
        got_inst, got_proof = decode_calldata(data, 2)
        assert (got_inst, got_proof) == (inst, proof)


class TestFixtures:
    def test_step_fixture_roundtrip(self, tmp_path):
        args = default_sync_step_args(TINY)
        p = str(tmp_path / "step.json")
        dump_step_fixture(args, p)
        back = load_step_fixture(p)
        assert back == args

    def test_rotation_fixture_roundtrip(self, tmp_path):
        args = default_committee_update_args(TINY)
        p = str(tmp_path / "rot.json")
        dump_rotation_fixture(args, p)
        assert load_rotation_fixture(p) == args


class TestSpectreContract:
    """Protocol tests with MockVerifiers (reference `contract-tests/tests/
    spectre.rs:34-110` — multi-system testing without an EVM)."""

    def _contract(self, period=0):
        return SpectreContract(spec=TINY, initial_sync_period=period,
                               initial_committee_poseidon=12345)

    def test_step_advances_head(self):
        args = default_sync_step_args(TINY)
        c = self._contract(TINY.sync_period(args.attested_header.slot))
        inp = StepInput(
            attested_slot=args.attested_header.slot,
            finalized_slot=args.finalized_header.slot,
            participation=sum(args.participation_bits),
            finalized_header_root=args.finalized_header.hash_tree_root(),
            execution_payload_root=args.execution_payload_root)
        c.step(inp, b"")
        assert c.head == args.finalized_header.slot
        assert c.block_header_roots[inp.finalized_slot] == inp.finalized_header_root

    def test_step_input_encoding_matches_circuit(self):
        # Solidity toPublicInputsCommitment == circuit get_instances[0]
        # (reference `step_input_encoding.rs:109-116`)
        args = default_sync_step_args(TINY)
        inp = StepInput(
            attested_slot=args.attested_header.slot,
            finalized_slot=args.finalized_header.slot,
            participation=sum(args.participation_bits),
            finalized_header_root=args.finalized_header.hash_tree_root(),
            execution_payload_root=args.execution_payload_root)
        assert inp.to_public_inputs_commitment() == \
            StepCircuit.get_instances(args, TINY)[0]

    def test_step_rejects_low_participation(self):
        c = self._contract(TINY.sync_period(10))
        inp = StepInput(attested_slot=10, finalized_slot=9, participation=1,
                        finalized_header_root=b"\x00" * 32,
                        execution_payload_root=b"\x00" * 32)
        with pytest.raises(AssertionError, match="participation"):
            c.step(inp, b"")

    def test_rotate_flow(self):
        c = self._contract()
        args = default_committee_update_args(TINY)
        fin_slot = args.finalized_header.slot
        root = args.finalized_header.hash_tree_root()
        c.block_header_roots[fin_slot] = root
        inst = CommitteeUpdateCircuit.get_instances(args, TINY)
        c.rotate(fin_slot, inst[0], inst[1], inst[2], b"")
        next_period = TINY.sync_period(fin_slot) + 1
        assert c.sync_committee_poseidons[next_period] == inst[0]
        # double rotation refused
        with pytest.raises(AssertionError, match="already rotated"):
            c.rotate(fin_slot, inst[0], inst[1], inst[2], b"")

    def test_rotate_rejects_wrong_root(self):
        c = self._contract()
        c.block_header_roots[100] = b"\x01" * 32
        with pytest.raises(AssertionError, match="header root mismatch"):
            c.rotate(100, 1, 2, 3, b"")


class _FakeState:
    """Canned prover for RPC plumbing tests (real proving is minutes)."""

    def __init__(self, spec, concurrency=1, delay=0.0):
        self.spec = spec
        self.concurrency = concurrency
        self.delay = delay
        self.active = 0
        self.max_active = 0
        self._lock = threading.Lock()

    def _track(self):
        import contextlib
        import time

        @contextlib.contextmanager
        def cm():
            with self._lock:
                self.active += 1
                self.max_active = max(self.max_active, self.active)
            try:
                if self.delay:
                    time.sleep(self.delay)
                yield
            finally:
                with self._lock:
                    self.active -= 1
        return cm()

    def prove_step(self, args):
        with self._track():
            return b"\x01" * 64, StepCircuit.get_instances(args, self.spec)

    def prove_committee(self, args):
        with self._track():
            return (b"\x02" * 64,
                    CommitteeUpdateCircuit.get_instances(args, self.spec))


class TestBatchProveAPI:
    def test_batch_preserves_order_and_concurrency(self):
        """prove_*_batch: the DP governor maps requests over a pool sized by
        the configured concurrency, results in request order (the proving
        itself is exercised by the prover tests; this pins the batch API)."""
        import threading
        import time

        from spectre_tpu.prover_service.state import ProverState

        seen = []

        class S(ProverState):
            def __init__(self):
                self.concurrency = 2

            def prove_step(self, args):
                seen.append((args, threading.get_ident()))
                time.sleep(0.02)
                return (b"proof-%d" % args, [args])

        s = S()
        out = s.prove_step_batch([3, 1, 2])
        assert out == [(b"proof-3", [3]), (b"proof-1", [1]),
                       (b"proof-2", [2])]
        assert len({t for _, t in seen}) >= 2   # ran on >1 worker


def _step_request_params(args):
    from spectre_tpu.fields import bls12_381 as bls
    pks = [("0x" + bls.g1_compress((bls.Fq(x), bls.Fq(y))).hex())
           for x, y in args.pubkeys_uncompressed]
    update = {
        "attested_header": _hdr_dict(args.attested_header),
        "finalized_header": _hdr_dict(args.finalized_header),
        "finality_branch": ["0x" + b.hex() for b in args.finality_branch],
        "execution_payload_root": "0x" + args.execution_payload_root.hex(),
        "execution_branch": ["0x" + b.hex()
                             for b in args.execution_payload_branch],
        "sync_aggregate": {
            "sync_committee_bits": args.participation_bits,
            "sync_committee_signature": "0x" + args.signature_compressed.hex(),
        },
    }
    return {"light_client_finality_update": update, "pubkeys": pks,
            "domain": "0x" + args.domain.hex()}


def _rpc_post(port, payload, raw=None, timeout=600):
    body = raw if raw is not None else json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/rpc", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


class TestRPC:
    def test_rpc_roundtrip(self):
        from spectre_tpu.prover_service.rpc import serve
        state = _FakeState(TINY)
        server = serve(state, port=0, background=True)
        port = server.server_address[1]
        try:
            args = default_sync_step_args(TINY)
            data = _rpc_post(port, {
                "jsonrpc": "2.0", "id": 1,
                "method": "genEvmProof_SyncStepCompressed",
                "params": _step_request_params(args)})
            assert "result" in data, data
            want = StepCircuit.get_instances(args, TINY)
            assert [int(v, 16) for v in data["result"]["instances"]] == want
            # unknown method -> JSON-RPC error
            data2 = _rpc_post(port, {"jsonrpc": "2.0", "id": 2,
                                     "method": "nope", "params": {}},
                              timeout=60)
            assert data2["error"]["code"] == -32601
        finally:
            server.shutdown()

    def test_error_taxonomy(self):
        """Parsing, envelope validation and dispatch are separate failure
        domains (ISSUE-3 satellite): malformed JSON is -32700, a non-dict
        or jsonrpc-less body -32600, and an internal prover error -32603
        with a sanitized message — never a bogus 'parse error'."""
        from spectre_tpu.prover_service.rpc import serve

        class Boom(_FakeState):
            def prove_step(self, args):
                raise RuntimeError("secret internal path /opt/x leaked")

        server = serve(Boom(TINY), port=0, background=True)
        port = server.server_address[1]
        try:
            # malformed JSON -> parse error
            data = _rpc_post(port, None, raw=b"{nope", timeout=60)
            assert data["error"]["code"] == -32700
            # valid JSON, not an object -> invalid request
            data = _rpc_post(port, [1, 2, 3], timeout=60)
            assert data["error"]["code"] == -32600
            # object without jsonrpc member -> invalid request
            data = _rpc_post(port, {"method": "ping", "id": 1}, timeout=60)
            assert data["error"]["code"] == -32600
            # dispatch blow-up -> internal error, sanitized (class name
            # only, no exception text on the wire)
            args = default_sync_step_args(TINY)
            data = _rpc_post(port, {
                "jsonrpc": "2.0", "id": 4,
                "method": "genEvmProof_SyncStepCompressed",
                "params": _step_request_params(args)})
            assert data["error"]["code"] == -32603
            assert "secret internal path" not in data["error"]["message"]
            assert "RuntimeError" in data["error"]["message"]
            # missing params -> invalid params, not internal error
            data = _rpc_post(port, {
                "jsonrpc": "2.0", "id": 5,
                "method": "genEvmProof_SyncStepCompressed", "params": {}},
                timeout=60)
            assert data["error"]["code"] == -32602
        finally:
            server.shutdown()


class TestAsyncRPC:
    def test_submit_poll_result_matches_blocking(self):
        """ISSUE-3 acceptance: submit -> poll -> result equals the blocking
        genEvmProof_* result for the same witness (and dedups onto the
        same job)."""
        from spectre_tpu.prover_service.rpc import serve
        state = _FakeState(TINY)
        server = serve(state, port=0, background=True)
        port = server.server_address[1]
        try:
            args = default_sync_step_args(TINY)
            params = _step_request_params(args)
            blocking = _rpc_post(port, {
                "jsonrpc": "2.0", "id": 1,
                "method": "genEvmProof_SyncStepCompressed",
                "params": params})["result"]
            sub = _rpc_post(port, {
                "jsonrpc": "2.0", "id": 2,
                "method": "submitProof_SyncStepCompressed",
                "params": params})["result"]
            jid = sub["job_id"]
            # same witness digest -> dedup onto the already-proved job
            assert sub["status"] == "done"
            for _ in range(100):
                st = _rpc_post(port, {"jsonrpc": "2.0", "id": 3,
                                      "method": "getProofStatus",
                                      "params": {"job_id": jid}},
                               timeout=60)["result"]
                if st["status"] in ("done", "failed"):
                    break
                import time
                time.sleep(0.05)
            assert st["status"] == "done"
            result = _rpc_post(port, {"jsonrpc": "2.0", "id": 4,
                                      "method": "getProofResult",
                                      "params": {"job_id": jid}},
                               timeout=60)["result"]
            assert result == blocking
            # unknown job id -> typed error
            err = _rpc_post(port, {"jsonrpc": "2.0", "id": 5,
                                   "method": "getProofResult",
                                   "params": {"job_id": "nope"}},
                            timeout=60)["error"]
            assert err["code"] == -32004
        finally:
            server.shutdown()

    def test_concurrent_submits_respect_cap(self):
        """N async submissions drain at the configured concurrency: the
        worker-pool size mirrors ProverState's semaphore cap."""
        from spectre_tpu.prover_service.jobs import ensure_jobs
        state = _FakeState(TINY, concurrency=2, delay=0.05)
        runner_calls = []

        def runner(method, params):
            runner_calls.append(method)
            _, inst = state.prove_step(default_sync_step_args(TINY))
            return {"instances": [hex(v) for v in inst]}

        q = ensure_jobs(state, runner=runner)
        jids = [q.submit("m", {"w": i}) for i in range(6)]
        for jid in jids:
            assert q.wait(jid, timeout=30).status == "done"
        assert len(runner_calls) == 6
        assert state.max_active <= 2       # cap honored
        assert state.max_active == 2       # ...and actually used
        q.stop()

    def test_healthz_endpoint(self):
        from spectre_tpu.prover_service.rpc import serve
        state = _FakeState(TINY)
        server = serve(state, port=0, background=True)
        port = server.server_address[1]
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=60) as resp:
                data = json.load(resp)
            assert data["status"] == "ok"
            assert "counters" in data and "jobs" in data
            # the RPC method view carries the same counters
            h = _rpc_post(port, {"jsonrpc": "2.0", "id": 1,
                                 "method": "health", "params": {}},
                          timeout=60)["result"]
            assert "counters" in h
            assert "beacon_breakers" in h
        finally:
            server.shutdown()

    def test_healthz_not_ready_when_breaker_open(self):
        """ROADMAP PR-3 follow-up (ISSUE 4 satellite): an OPEN beacon
        circuit breaker turns the readiness probe into a 503 with the
        breaker state in the body; once the breaker leaves the open state
        (cooldown -> half-open trial) readiness returns to 200."""
        import time
        import urllib.error

        from spectre_tpu.preprocessor.beacon import (BeaconClient,
                                                     CircuitBreakerOpen)
        from spectre_tpu.prover_service.rpc import serve
        from spectre_tpu.utils import faults
        state = _FakeState(TINY)
        server = serve(state, port=0, background=True)
        port = server.server_address[1]
        client = BeaconClient("http://127.0.0.1:9/", retries=0,
                              breaker_threshold=1, breaker_cooldown=0.2,
                              total_timeout=5.0, sleep=lambda _s: None)
        try:
            faults.install_plan("beacon.fetch:connreset:1")
            # threshold=1: the injected failure trips the breaker mid-call
            with pytest.raises(CircuitBreakerOpen):
                client._get("/eth/v1/anything")
            assert client.breaker_state == "open"
            req = urllib.request.Request(f"http://127.0.0.1:{port}/healthz")
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=60)
            assert e.value.code == 503
            body = json.load(e.value)
            assert body["status"] == "degraded"
            assert any(b["state"] == "open"
                       for b in body["beacon_breakers"])
            # cooldown elapses -> half-open admits a trial -> ready again
            time.sleep(0.25)
            assert client.breaker_state == "half-open"
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=60) as resp:
                data = json.load(resp)
            assert data["status"] == "ok"
        finally:
            faults.clear()
            del client
            server.shutdown()


class TestProverClient:
    def test_typed_rpc_error(self):
        from spectre_tpu.prover_service.rpc import serve
        from spectre_tpu.prover_service.rpc_client import ProverClient, RpcError
        server = serve(_FakeState(TINY), port=0, background=True)
        port = server.server_address[1]
        try:
            client = ProverClient(f"http://127.0.0.1:{port}/rpc", timeout=60)
            assert client.ping() == "pong"
            with pytest.raises(RpcError) as e:
                client._call("definitelyNotAMethod", {})
            assert e.value.code == -32601
            assert "unknown method" in e.value.message
        finally:
            server.shutdown()

    def test_retries_once_on_connection_reset(self, monkeypatch):
        from spectre_tpu.prover_service import rpc_client as rc
        calls = []

        class _Resp:
            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

            def read(self):
                return json.dumps({"jsonrpc": "2.0", "result": "pong",
                                   "id": 1}).encode()

        def flaky(req, timeout=None):
            calls.append(timeout)
            if len(calls) == 1:
                raise ConnectionResetError("injected reset")
            return _Resp()

        monkeypatch.setattr(rc.urllib.request, "urlopen", flaky)
        client = rc.ProverClient("http://127.0.0.1:1/rpc", timeout=5)
        assert client.ping() == "pong"
        assert len(calls) == 2             # one reset, one retry
        # a second reset in a row (fresh call) still fails after the
        # single retry
        calls.clear()

        def always_reset(req, timeout=None):
            calls.append(timeout)
            raise ConnectionResetError("injected reset")

        monkeypatch.setattr(rc.urllib.request, "urlopen", always_reset)
        with pytest.raises(ConnectionResetError):
            client.ping()
        # two prove attempts, then ONE membership probe (ISSUE 18: the
        # exhausted rotation asks `health` for fresh replica URLs before
        # failing hard; here it resets too, so the original error wins)
        assert len(calls) == 3

    def test_refreshes_endpoints_from_membership_when_exhausted(
            self, monkeypatch):
        """ISSUE-18 satellite: once the conn-reset rotation has burned
        every configured URL, the client asks the dispatcher membership
        (`health` RPC) for replica URLs it doesn't know yet and retries
        against the adopted fleet before failing hard."""
        from spectre_tpu.prover_service import rpc_client as rc
        calls = []
        fresh = "http://127.0.0.1:7103"

        class _Resp:
            def __init__(self, payload):
                self._payload = payload

            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

            def read(self):
                return json.dumps(self._payload).encode()

        def fake(req, timeout=None):
            url, body = req.full_url, json.loads(req.data)
            calls.append((url, body["method"]))
            if body["method"] == "health":
                if url == fresh:
                    raise ConnectionResetError("still dead")
                return _Resp({"jsonrpc": "2.0", "id": 1, "result": {
                    "dispatcher": {"replicas": [
                        {"replica_id": "r-new", "url": fresh},
                        {"replica_id": "r-old",
                         "url": "http://127.0.0.1:7101"}]}}})
            if url == fresh:
                return _Resp({"jsonrpc": "2.0", "result": "pong", "id": 1})
            raise ConnectionResetError("injected reset")

        monkeypatch.setattr(rc.urllib.request, "urlopen", fake)
        client = rc.ProverClient(["http://127.0.0.1:7101",
                                  "http://127.0.0.1:7102"],
                                 timeout=5, conn_retries=1,
                                 sleep=lambda s: None)
        assert client.ping() == "pong"
        assert client.endpoint_refreshes == 1
        assert client.urls[-1] == fresh       # adopted, not replaced
        assert client.url == fresh            # and now current
        # the already-known url in the snapshot was NOT duplicated
        assert client.urls.count("http://127.0.0.1:7101") == 1
        # ping: reset on 7101, rotate-reset on 7102, health probe, retry
        assert [m for _, m in calls].count("health") == 1

    def test_refresh_failure_still_raises(self, monkeypatch):
        """When no endpoint serves a membership snapshot the original
        conn-reset surfaces unchanged — no infinite refresh loop."""
        from spectre_tpu.prover_service import rpc_client as rc

        def always_reset(req, timeout=None):
            raise ConnectionResetError("injected reset")

        monkeypatch.setattr(rc.urllib.request, "urlopen", always_reset)
        client = rc.ProverClient(["http://127.0.0.1:7101",
                                  "http://127.0.0.1:7102"],
                                 timeout=5, conn_retries=1,
                                 sleep=lambda s: None)
        with pytest.raises(ConnectionResetError):
            client.ping()
        assert client.endpoint_refreshes == 0

    def test_get_update_cached_honors_304(self, tmp_path):
        """ISSUE-14 satellite: the client-side digest cache sends
        If-None-Match and re-serves the cached decode on 304, so a
        sealed update crosses the wire at most once per client."""
        from spectre_tpu.follower.updates import UpdateStore
        from spectre_tpu.gateway import Gateway
        from spectre_tpu.prover_service.rpc import serve
        from spectre_tpu.prover_service.rpc_client import (ProverClient,
                                                           RpcError)
        store = UpdateStore(str(tmp_path))
        for p in range(3, 8):
            store.append_committee(p, {"proof": "0x" + "ab" * 8,
                                       "committee_poseidon": hex(p * 7 + 1),
                                       "instances": [hex(p)]})
        server = serve(_FakeState(TINY), port=0, background=True,
                       gateway=Gateway(store, pack_periods=2))
        port = server.server_address[1]
        try:
            client = ProverClient(f"http://127.0.0.1:{port}/rpc",
                                  timeout=60)
            first = client.get_update_cached(4)
            assert first["period"] == 4
            assert client.cache_304s == 0
            assert client.get_update_cached(4) == first   # revalidated
            assert client.cache_304s == 1
            rng = client.get_update_range_cached(3, count=3)
            assert [u["period"] for u in rng["updates"]] == [3, 4, 5]
            assert client.get_update_range_cached(3, count=3) == rng
            assert client.cache_304s == 2
            boot = client.get_bootstrap_cached()
            assert boot["anchor_period"] == 3 and boot["tip_period"] == 7
            with pytest.raises(RpcError) as e:
                client.get_update_cached(99)
            assert e.value.code == -32007
            # distinct keys stay independently cached; the 404 does not
            assert len(client._etag_cache) == 3
        finally:
            server.shutdown()

    def test_gateway_routes_404_without_mount(self):
        """GET /v1/* on a server launched without --gateway is a plain
        404, not a crash in the RPC handler."""
        import urllib.error
        import urllib.request
        from spectre_tpu.prover_service.rpc import serve
        server = serve(_FakeState(TINY), port=0, background=True)
        port = server.server_address[1]
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/v1/bootstrap", timeout=30)
            assert e.value.code == 404
        finally:
            server.shutdown()


class TestWaitForProofDeadline:
    """ISSUE 10 satellite: ONE overall deadline bounds wait_for_proof —
    slow HTTP round trips, per-poll timeouts and overload-retry sleeps
    all count against it, so a slow server cannot stretch the wait."""

    def _client(self, clk, **kw):
        from spectre_tpu.prover_service.rpc_client import ProverClient

        def sleep(s):
            clk["t"] += s
        kw.setdefault("rng", lambda: 0.0)   # no jitter: deterministic
        return ProverClient("http://127.0.0.1:1/rpc", timeout=3600,
                            sleep=sleep, clock=lambda: clk["t"], **kw)

    def test_slow_polls_cannot_stretch_past_deadline(self):
        clk = {"t": 0.0}
        client = self._client(clk)
        seen_timeouts = []

        def slow_call(method, params, timeout=None):
            seen_timeouts.append(timeout)
            clk["t"] += 40.0            # each HTTP round trip eats 40 s
            return {"status": "running"}

        client._call = slow_call
        with pytest.raises(TimeoutError, match="still running"):
            client.wait_for_proof("j1", poll=1.0, timeout=100.0)
        # polls at t=0/41/82; t=123 > 100 so NO fourth poll starts
        assert len(seen_timeouts) == 3
        assert clk["t"] < 130.0
        # per-call HTTP timeout is clamped to the time remaining
        assert seen_timeouts[0] == 30.0            # min(3600, 30, 100)
        assert seen_timeouts[2] == pytest.approx(18.0)   # 100 - 82 left

    def test_overload_backoff_capped_by_deadline(self):
        from spectre_tpu.prover_service.rpc_client import RpcError
        clk = {"t": 0.0}
        client = self._client(clk, retry_after_cap=100.0)
        calls = []

        def shedding_call(method, params, timeout=None):
            calls.append(clk["t"])
            raise RpcError(-32001, "service overloaded", retry_after=50.0)

        client._call = shedding_call
        with pytest.raises(RpcError) as e:
            client.wait_for_proof("j1", poll=1.0, timeout=60.0)
        assert e.value.code == -32001
        # first shed sleeps its 50 s hint (fits); the second backoff
        # would land at t=100 > 60 so the error surfaces immediately
        assert calls == [0.0, 50.0]
        assert clk["t"] == 50.0                    # never slept past deadline

    def test_no_timeout_waits_indefinitely(self):
        clk = {"t": 0.0}
        client = self._client(clk)
        states = iter(["queued", "running", "done"])

        def call(method, params, timeout=None):
            if method == "getProofStatus":
                return {"status": next(states)}
            return {"proof": "0x01"}

        client._call = call
        assert client.wait_for_proof("j1", poll=1.0)["proof"] == "0x01"


class TestOverloadRPC:
    """ISSUE 6: a shed submission surfaces as HTTP 429 + Retry-After on
    the transport AND `-32001 service overloaded` (with data.retry_after_s)
    in the JSON-RPC envelope; the typed client honors the hint."""

    def _overloaded_server(self):
        # queue_depth=0: every fresh submission sheds (deterministic)
        from spectre_tpu.prover_service.rpc import serve
        server = serve(_FakeState(TINY), port=0, background=True,
                       queue_depth=0)
        return server, server.server_address[1]

    def test_429_retry_after_and_rpc_envelope(self):
        import urllib.error
        server, port = self._overloaded_server()
        try:
            body = json.dumps({
                "jsonrpc": "2.0", "id": 1,
                "method": "submitProof_SyncStepCompressed",
                "params": _step_request_params(
                    default_sync_step_args(TINY))}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/rpc", data=body,
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=60)
            assert e.value.code == 429
            assert int(e.value.headers["Retry-After"]) >= 1
            err = json.load(e.value)["error"]
            assert err["code"] == -32001
            assert err["data"]["retry_after_s"] >= 1.0
        finally:
            server.shutdown()

    def test_client_surfaces_retry_after(self):
        from spectre_tpu.prover_service.rpc_client import (ProverClient,
                                                           RpcError)
        server, port = self._overloaded_server()
        try:
            sleeps = []
            client = ProverClient(f"http://127.0.0.1:{port}/rpc",
                                  timeout=60, overload_retries=1,
                                  sleep=sleeps.append, rng=lambda: 0.0)
            params = _step_request_params(default_sync_step_args(TINY))
            with pytest.raises(RpcError) as e:
                client.submit_sync_step(
                    params["light_client_finality_update"],
                    params["pubkeys"], params["domain"])
            assert e.value.code == -32001
            assert e.value.retry_after is not None
            # the ONE bounded retry slept the server's hint before giving up
            assert len(sleeps) == 1
            assert sleeps[0] == pytest.approx(e.value.retry_after)
        finally:
            server.shutdown()

    def test_client_shedding_retry_then_success(self, monkeypatch):
        from spectre_tpu.prover_service.rpc import SERVICE_OVERLOADED
        from spectre_tpu.prover_service.rpc_client import (ProverClient,
                                                           RpcError)
        sleeps = []
        client = ProverClient("http://127.0.0.1:1/rpc", overload_retries=2,
                              retry_after_cap=30.0, sleep=sleeps.append,
                              rng=lambda: 0.0)
        calls = {"n": 0}

        def fake_call(method, params, timeout=None):
            calls["n"] += 1
            if calls["n"] < 3:
                raise RpcError(SERVICE_OVERLOADED, "service overloaded",
                               retry_after=2.5)
            return {"job_id": "j1"}

        monkeypatch.setattr(client, "_call", fake_call)
        assert client._call_shedding("m", {}) == {"job_id": "j1"}
        assert calls["n"] == 3
        assert sleeps == [2.5, 2.5]        # server hint honored, rng=0
        # an oversized hint is CAPPED (a shed must not park clients)
        sleeps.clear()
        calls["n"] = 0

        def fake_call_big(method, params, timeout=None):
            calls["n"] += 1
            if calls["n"] < 2:
                raise RpcError(SERVICE_OVERLOADED, "service overloaded",
                               retry_after=900.0)
            return {"job_id": "j2"}

        monkeypatch.setattr(client, "_call", fake_call_big)
        assert client._call_shedding("m", {}) == {"job_id": "j2"}
        assert sleeps == [30.0]

    def test_job_not_done_moved_to_32002(self):
        from spectre_tpu.prover_service.rpc import serve
        server = serve(_FakeState(TINY, delay=0.5), port=0, background=True)
        port = server.server_address[1]
        try:
            sub = _rpc_post(port, {
                "jsonrpc": "2.0", "id": 1,
                "method": "submitProof_SyncStepCompressed",
                "params": _step_request_params(
                    default_sync_step_args(TINY))}, timeout=60)["result"]
            err = _rpc_post(port, {"jsonrpc": "2.0", "id": 2,
                                   "method": "getProofResult",
                                   "params": {"job_id": sub["job_id"]}},
                            timeout=60)["error"]
            # -32001 now means "service overloaded"; pending moved here
            assert err["code"] == -32002
        finally:
            server.shutdown()

    def test_deadline_s_threads_through_rpc(self):
        from spectre_tpu.prover_service.rpc import serve
        server = serve(_FakeState(TINY, delay=1.0), port=0, background=True)
        port = server.server_address[1]
        try:
            params = _step_request_params(default_sync_step_args(TINY))
            params["deadline_s"] = 0.05
            jid = _rpc_post(port, {
                "jsonrpc": "2.0", "id": 1,
                "method": "submitProof_SyncStepCompressed",
                "params": params}, timeout=60)["result"]["job_id"]
            import time
            for _ in range(200):
                st = _rpc_post(port, {"jsonrpc": "2.0", "id": 2,
                                      "method": "getProofStatus",
                                      "params": {"job_id": jid}},
                               timeout=60)["result"]
                if st["status"] in ("done", "failed", "cancelled"):
                    break
                time.sleep(0.05)
            assert st["status"] == "failed"   # clamped by the client deadline
        finally:
            server.shutdown()


class TestCancelRace:
    """ISSUE 6 satellite: cancelProof racing completion must NOT resurrect
    a terminal job or delete its stored artifact."""

    def test_cancel_after_done_is_noop(self, tmp_path):
        import os
        from spectre_tpu.prover_service.jobs import JobQueue

        def runner(method, params):
            return {"proof": "0xfeed", "w": params["w"]}

        q = JobQueue(runner, concurrency=1, journal_dir=str(tmp_path))
        jid = q.submit("m", {"w": 1})
        job = q.wait(jid, timeout=10)
        assert job.status == "done"
        apath = q.store.path_for(job.result_digest)
        assert os.path.exists(apath)
        assert q.cancel(jid) is False        # terminal: cancel refused
        assert q.status(jid)["status"] == "done"
        assert q.result(jid).result == {"proof": "0xfeed", "w": 1}
        assert os.path.exists(apath)         # artifact untouched
        # restart still serves the result (journal unpolluted by the race)
        q.stop()
        q2 = JobQueue(runner, concurrency=1, journal_dir=str(tmp_path))
        assert q2.result(jid).result == {"proof": "0xfeed", "w": 1}
        q2.stop()

    def test_cancel_mid_run_still_cancels(self, tmp_path):
        from spectre_tpu.prover_service.jobs import JobQueue
        started = threading.Event()
        gate = threading.Event()

        def runner(method, params):
            started.set()
            gate.wait(timeout=30)
            return {"proof": "0xdead"}

        q = JobQueue(runner, concurrency=1, journal_dir=str(tmp_path))
        jid = q.submit("m", {"w": 2})
        assert started.wait(timeout=10)
        assert q.cancel(jid) is True
        gate.set()
        job = q.wait(jid, timeout=10)
        assert job.status == "cancelled"
        assert job.result is None            # late result discarded
        q.stop()


class TestCLI:
    def test_parser(self):
        from spectre_tpu.prover_service.cli import main
        with pytest.raises(SystemExit) as e:
            main(["--help"])
        assert e.value.code == 0
        with pytest.raises(SystemExit):
            main(["circuit", "bogus", "setup"])


class TestProfiling:
    def test_phase_timers(self):
        from spectre_tpu.utils import profiling as prof
        prof.reset()
        with prof.phase("unit/test"):
            pass
        t = prof.totals()
        assert t["unit/test"]["count"] == 1
        prof.reset()
        assert prof.totals() == {}


class TestEmittedSpectreSol:
    """The EMITTED Spectre.sol executes the same protocol flows as the
    Python model (reference: `contract-tests/tests/spectre.rs:34-110` runs
    the deployed contract with MockVerifiers; here the generated source is
    interpreted statement-by-statement)."""

    def _contract(self, period=2, poseidon=0x1234):
        from spectre_tpu.contracts.sol_gen import SolSpectre
        return SolSpectre(TINY, period, poseidon, MockVerifier(),
                          MockVerifier())

    def _step_input(self, **kw):
        d = dict(attested_slot=2 * TINY.slots_per_period + 5,
                 finalized_slot=2 * TINY.slots_per_period + 1,
                 participation=2,
                 finalized_header_root=b"\xAA" * 32,
                 execution_payload_root=b"\xBB" * 32)
        d.update(kw)
        return StepInput(**d)

    def test_sol_source_emitted(self, tmp_path):
        from spectre_tpu.contracts.sol_gen import gen_spectre_sol
        src = gen_spectre_sol(TINY)
        assert "contract Spectre" in src and "function step" in src
        p = tmp_path / "Spectre.sol"
        p.write_text(src)
        assert p.stat().st_size > 2000

    def test_step_advances_head_like_model(self):
        c = self._contract()
        inp = self._step_input()
        c.step(inp, b"")
        assert c.head == inp.finalized_slot
        assert c.storage["blockHeaderRoots"][inp.finalized_slot] == \
            int.from_bytes(inp.finalized_header_root, "big")
        # model comparison
        m = SpectreContract(spec=TINY, initial_sync_period=2,
                            initial_committee_poseidon=0x1234)
        m.step(inp, b"")
        assert m.head == c.head

    def test_commitment_matches_python_and_circuit_encoding(self):
        """Solidity toPublicInputsCommitment == StepInput model ==
        the circuit's instance encoding (`step_input_encoding.rs:109-116`)."""
        c = self._contract()
        inp = self._step_input()
        sin = {"attestedSlot": inp.attested_slot,
               "finalizedSlot": inp.finalized_slot,
               "participation": inp.participation,
               "finalizedHeaderRoot": int.from_bytes(
                   inp.finalized_header_root, "big"),
               "executionPayloadRoot": int.from_bytes(
                   inp.execution_payload_root, "big")}
        got = c.call("toPublicInputsCommitment", sin)
        assert got == inp.to_public_inputs_commitment()

    def test_step_rejects_low_participation(self):
        from spectre_tpu.contracts.sol_gen import SolRevert
        c = self._contract()
        inp = self._step_input(participation=1)
        with pytest.raises(SolRevert, match="insufficient participation"):
            c.step(inp, b"")

    def test_step_rejects_unknown_period(self):
        from spectre_tpu.contracts.sol_gen import SolRevert
        c = self._contract(period=0)
        with pytest.raises(SolRevert, match="no committee"):
            c.step(self._step_input(), b"")

    def test_rotate_flow_and_replay_protection(self):
        from spectre_tpu.contracts.sol_gen import SolRevert
        c = self._contract()
        inp = self._step_input()
        c.step(inp, b"")
        root = inp.finalized_header_root
        lo = int.from_bytes(root[16:], "big")
        hi = int.from_bytes(root[:16], "big")
        c.rotate(inp.finalized_slot, 0x777, lo, hi, b"")
        next_period = TINY.sync_period(inp.finalized_slot) + 1
        assert c.storage["syncCommitteePoseidons"][next_period] == 0x777
        with pytest.raises(SolRevert, match="already rotated"):
            c.rotate(inp.finalized_slot, 0x888, lo, hi, b"")
        with pytest.raises(SolRevert, match="header root mismatch"):
            c.rotate(inp.finalized_slot + 0, 0x999, lo + 1, hi, b"")

    def test_rejecting_verifier_blocks_step(self):
        from spectre_tpu.contracts.sol_gen import SolRevert, SolSpectre

        class Reject:
            def verify(self, instances, proof):
                return False

        c = SolSpectre(TINY, 2, 0x1234, Reject(), Reject())
        with pytest.raises(SolRevert, match="step proof invalid"):
            c.step(self._step_input(), b"")


class TestOutputIntegrityRPC:
    """ISSUE 9: the verify-before-serve layer as seen from the wire."""

    def test_healthz_gates_on_self_check(self):
        """A failing prove+verify self-check turns readiness into a 503
        with `self_check` in the body; a subsequent passing run restores
        200. The `health` RPC view carries the same snapshot."""
        import urllib.error

        from spectre_tpu.prover_service.rpc import serve
        from spectre_tpu.prover_service.selfverify import SelfCheck

        state = _FakeState(TINY)
        ok_box = {"ok": False}
        state.self_check = SelfCheck(runner=lambda: ok_box["ok"])
        state.self_check.run()
        server = serve(state, port=0, background=True)
        port = server.server_address[1]
        try:
            req = urllib.request.Request(f"http://127.0.0.1:{port}/healthz")
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=60)
            assert e.value.code == 503
            body = json.load(e.value)
            assert body["status"] == "degraded"
            assert body["self_check"] == {"ok": False, "runs": 1,
                                          "last_error":
                                          "tiny-circuit proof failed "
                                          "verification"}
            ok_box["ok"] = True
            state.self_check.run()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=60) as resp:
                data = json.load(resp)
            assert data["status"] == "ok"
            assert data["self_check"]["ok"] is True
            h = _rpc_post(port, {"jsonrpc": "2.0", "id": 1,
                                 "method": "health", "params": {}},
                          timeout=60)["result"]
            assert h["self_check"]["runs"] == 2
        finally:
            server.shutdown()

    def test_proof_verify_failed_sanitized_over_rpc(self):
        """A twice-failed self-verify surfaces as -32005 with the typed
        sanitized message — no traceback, no internals."""
        from spectre_tpu.prover_service.rpc import JOB_FAILED, serve
        from spectre_tpu.prover_service.selfverify import ProofVerifyFailed

        class _SdcState(_FakeState):
            def prove_step(self, args):
                raise ProofVerifyFailed("step")

        server = serve(_SdcState(TINY), port=0, background=True)
        port = server.server_address[1]
        try:
            args = default_sync_step_args(TINY)
            data = _rpc_post(port, {
                "jsonrpc": "2.0", "id": 1,
                "method": "genEvmProof_SyncStepCompressed",
                "params": _step_request_params(args)}, timeout=120)
            assert data["error"]["code"] == JOB_FAILED
            msg = data["error"]["message"]
            assert msg.startswith("proof failed self-verification")
            assert "quarantined" in msg
            assert "Traceback" not in msg and "File \"" not in msg
        finally:
            server.shutdown()

    def test_scrub_now_rpc(self, tmp_path):
        """scrubNow runs one scrubber pass over the queue's store and
        returns its summary; a hand-corrupted orphan is quarantined."""
        import os

        from spectre_tpu.prover_service.rpc import serve

        state = _FakeState(TINY)
        server = serve(state, port=0, background=True,
                       journal_dir=str(tmp_path), scrub_interval=0)
        port = server.server_address[1]
        try:
            store = state.jobs.store
            digest = store.write(b"rot me over rpc")
            path = store.path_for(digest)
            with open(path, "r+b") as f:
                f.seek(1)
                f.write(b"\xee")
            res = _rpc_post(port, {"jsonrpc": "2.0", "id": 1,
                                   "method": "scrubNow", "params": {}},
                            timeout=60)["result"]
            assert res["corrupt"] == 1
            assert res["scanned"] == 1
            assert not os.path.exists(path)
            assert os.path.exists(os.path.join(
                store.quarantine_dir, os.path.basename(path)))
        finally:
            state.jobs.stop()
            server.shutdown()

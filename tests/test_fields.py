"""Host field/curve/pairing oracle tests (BN254 + BLS12-381)."""

import secrets

import pytest

from spectre_tpu.fields import bls12_381 as bls
from spectre_tpu.fields import bn254 as bn
from spectre_tpu.fields.common import modinv, tonelli_shanks


class TestPrimeFieldBasics:
    def test_modinv(self):
        for _ in range(10):
            a = secrets.randbelow(bn.R - 1) + 1
            assert a * modinv(a, bn.R) % bn.R == 1

    def test_sqrt(self):
        for _ in range(10):
            a = secrets.randbelow(bn.P)
            s = tonelli_shanks(a * a % bn.P, bn.P)
            assert s is not None and s * s % bn.P == a * a % bn.P

    def test_field_ops(self):
        a, b = bn.Fr.random(), bn.Fr.random()
        assert (a + b) - b == a
        assert (a * b) / b == a
        assert a ** 3 == a * a * a
        assert -a + a == bn.Fr.zero()


class TestExtField:
    def test_fq2_mul_inv(self):
        for F in (bn.Fq2, bls.Fq2):
            a = F.random()
            assert a * a.inv() == F.one()
            b = F.random()
            assert (a + b) * (a - b) == a * a - b * b

    def test_fq12_tower(self):
        for F in (bn.Fq12, bls.Fq12):
            a, b = F.random(), F.random()
            assert (a * b) / b == a
            assert a ** 5 == a * a * a * a * a

    def test_fq2_sqrt(self):
        a = bls.Fq2.random()
        s = (a * a).sqrt()
        assert s is not None and s * s == a * a


class TestBN254Curve:
    def test_generators_on_curve_and_order(self):
        assert bn.g1_curve.is_on_curve(bn.G1_GEN)
        assert bn.g2_curve.is_on_curve(bn.G2_GEN)
        assert bn.g1_curve.in_subgroup(bn.G1_GEN)
        assert bn.g2_curve.in_subgroup(bn.G2_GEN)

    def test_group_law(self):
        p2 = bn.g1_curve.double(bn.G1_GEN)
        p3 = bn.g1_curve.add(p2, bn.G1_GEN)
        assert p3 == bn.g1_curve.mul(bn.G1_GEN, 3)
        assert bn.g1_curve.add(p3, bn.g1_curve.neg(p3)) is None

    def test_root_of_unity(self):
        w = bn.fr_root_of_unity(10)
        assert pow(w, 1 << 10, bn.R) == 1
        assert pow(w, 1 << 9, bn.R) != 1

    def test_serialization(self):
        pt = bn.g1_curve.mul(bn.G1_GEN, 12345)
        assert bn.g1_from_bytes(bn.g1_to_bytes(pt)) == pt
        q = bn.g2_curve.mul(bn.G2_GEN, 999)
        assert bn.g2_from_bytes(bn.g2_to_bytes(q)) == q


class TestBN254Pairing:
    def test_bilinearity(self):
        e1 = bn.pairing(bn.G2_GEN, bn.G1_GEN)
        e2 = bn.pairing(bn.g2_curve.mul(bn.G2_GEN, 5), bn.g1_curve.mul(bn.G1_GEN, 7))
        assert e1 ** 35 == e2

    def test_pairing_check(self):
        # e(6*G1, G2) * e(-2*G1, 3*G2) == 1
        assert bn.pairing_check([
            (bn.g1_curve.mul(bn.G1_GEN, 6), bn.G2_GEN),
            (bn.g1_curve.neg(bn.g1_curve.mul(bn.G1_GEN, 2)), bn.g2_curve.mul(bn.G2_GEN, 3)),
        ])
        assert not bn.pairing_check([
            (bn.g1_curve.mul(bn.G1_GEN, 5), bn.G2_GEN),
            (bn.g1_curve.neg(bn.g1_curve.mul(bn.G1_GEN, 2)), bn.g2_curve.mul(bn.G2_GEN, 3)),
        ])


class TestBLS12381:
    def test_derived_cofactors_match_published(self):
        # cross-check the runtime derivation against the well-known values
        assert bls.H1 == 0x396C8C005555E1568C00AAAB0000AAAB
        assert bls.H2 == int(
            "0x5d543a95414e7f1091d50792876a202cd91de4547085abaa68a205b2e5a7ddfa628f1c"
            "b4d9e82ef21537e293a6691ae1616ec6e786f0c70cf1c38e31c7238e5", 16)

    def test_bilinearity(self):
        e1 = bls.pairing(bls.G2_GEN, bls.G1_GEN)
        e2 = bls.pairing(bls.g2_curve.mul(bls.G2_GEN, 3), bls.g1_curve.mul(bls.G1_GEN, 11))
        assert e1 ** 33 == e2

    def test_hash_to_g2_in_subgroup(self):
        h = bls.hash_to_g2(b"spectre_tpu test msg")
        assert bls.g2_curve.in_subgroup(h)

    def test_hash_to_g2_deterministic_and_dst_separated(self):
        assert bls.hash_to_g2(b"m") == bls.hash_to_g2(b"m")
        assert bls.hash_to_g2(b"m") != bls.hash_to_g2(b"m", dst=b"OTHER_DST_")

    def test_expand_message_xmd_shape(self):
        out = bls.expand_message_xmd(b"abc", b"DST", 100)
        assert len(out) == 100
        assert out != bls.expand_message_xmd(b"abd", b"DST", 100)

    def test_h_eff_structure(self):
        """h_eff must (a) clear the cofactor from a GENERIC (out-of-subgroup)
        point, (b) act as a unit mod r, and (c) relate to the plain cofactor
        as h_eff*P == m*(H2*P) with m = h_eff/H2 mod r — the documented
        'scalar equivalent of Budroni–Pintore' structure."""
        pt = bls._deterministic_twist_points(1)[0]
        assert not bls.g2_curve.in_subgroup(pt), \
            "test needs a generic point outside G2"
        cleared = bls.g2_curve.mul_unsafe(pt, bls.H_EFF_G2)
        assert cleared is not None and bls.g2_curve.in_subgroup(cleared)
        assert bls.H_EFF_G2 % bls.R != 0
        m = bls.H_EFF_G2 * pow(bls.g2_cofactor() % bls.R, -1, bls.R) % bls.R
        via_h2 = bls.g2_curve.mul_unsafe(bls.clear_cofactor_g2(pt), m)
        assert cleared == via_h2

    def test_svdw_variant_still_sound(self):
        # the round-1 SvdW path stays available (documented alternative);
        # it must still land in G2 and differ from the SSWU suite
        h = bls.hash_to_g2_svdw(b"svdw smoke")
        assert bls.g2_curve.in_subgroup(h)
        assert h != bls.hash_to_g2(b"svdw smoke")

    def test_sswu_iso_derivation_consistent(self):
        # the Velu-derived kernel/isogeny must reproduce the pinned
        # normalization constant among its c^6 = B2/b'' roots, and the map
        # must land on E2 (on-curve) for arbitrary field inputs
        xq, t, uq, cs = bls._iso3_constants()
        assert bls._ISO3_C in cs
        u = bls.Fq2([12345, 67890])
        p_iso = bls.map_to_curve_sswu_g2prime(u)
        # on the isogenous curve E2'
        x, y = p_iso
        assert y * y == x * x * x + bls.SSWU_A * x + bls.SSWU_B
        q = bls.iso3_map(p_iso)
        assert bls.g2_curve.is_on_curve(q)

    def test_sswu_matches_blst_fixture(self):
        """Interop anchor: the upstream 512-validator fixture was signed by
        the C blst library with the real eth2 ciphersuite. Our full pipeline
        (signing root -> hash_to_g2 SSWU -> pairing check) must accept it —
        this pins expand_message, SSWU, the derived isogeny, sgn0, h_eff, and
        the pairing all at once."""
        import os
        from spectre_tpu.test_utils import (REFERENCE_STEP_FIXTURE,
                                            load_reference_step_fixture)
        if not os.path.exists(REFERENCE_STEP_FIXTURE):
            pytest.skip("reference fixture unavailable")
        args = load_reference_step_fixture(REFERENCE_STEP_FIXTURE)
        sig = bls.g2_decompress(args.signature_compressed)
        pks = [(bls.Fq(x), bls.Fq(y))
               for (x, y), bit in zip(args.pubkeys_uncompressed,
                                      args.participation_bits) if bit]
        assert bls.fast_aggregate_verify(pks, args.signing_root(), sig)
        # and a mutated message must NOT verify
        assert not bls.fast_aggregate_verify(pks, b"\x00" * 32, sig)


class TestBLSSignatures:
    def test_aggregate_sign_verify(self):
        sks = [secrets.randbelow(bls.R) for _ in range(4)]
        pks = [bls.sk_to_pk(sk) for sk in sks]
        msg = b"attested header root"
        agg = bls.aggregate_signatures([bls.sign(sk, msg) for sk in sks])
        assert bls.fast_aggregate_verify(pks, msg, agg)
        assert not bls.fast_aggregate_verify(pks, b"wrong", agg)
        assert not bls.fast_aggregate_verify(pks[:3], msg, agg)

    def test_compression_roundtrip(self):
        sk = secrets.randbelow(bls.R)
        pk = bls.sk_to_pk(sk)
        sig = bls.g2_curve.mul(bls.G2_GEN, sk)
        assert bls.g1_decompress(bls.g1_compress(pk)) == pk
        assert bls.g2_decompress(bls.g2_compress(sig)) == sig
        assert bls.g1_decompress(bls.g1_compress(None)) is None
        assert bls.g2_decompress(bls.g2_compress(None)) is None

    def test_decompress_rejects_noncanonical(self):
        import pytest as _pt
        # infinity flag with nonzero payload
        with _pt.raises(AssertionError):
            bls.g1_decompress(b"\xc0" + b"\x01" + b"\x00" * 46)
        # x >= p
        with _pt.raises(AssertionError):
            bls.g1_decompress(b"\x9f" + b"\xff" * 47)
        # subgroup check catches cofactor points
        import secrets as _s
        while True:
            x = bls.Fq(_s.randbelow(bls.P))
            yy = (x * x * x + bls.B1).sqrt()
            if yy is not None:
                pt = (x, yy)
                break
        if not bls.g1_curve.in_subgroup(pt):  # overwhelmingly likely
            with _pt.raises(AssertionError):
                bls.g1_decompress(bls.g1_compress(pt), subgroup_check=True)

    def test_g1_compress_sign_bit(self):
        pk = bls.sk_to_pk(42)
        x, y = pk
        neg = (x, -y)
        assert bls.g1_compress(pk) != bls.g1_compress(neg)
        assert bls.g1_decompress(bls.g1_compress(neg)) == neg


class TestReviewRegressions:
    """Regressions for code-review findings on the initial math layer."""

    def test_no_infinity_forgery(self):
        # empty/identity pubkey+signature must NOT verify (eth2 KeyValidate)
        assert not bls.fast_aggregate_verify([], b"msg", None)
        assert not bls.verify(None, b"msg", None)
        assert not bls.fast_aggregate_verify([None, bls.sk_to_pk(1)], b"msg", None)

    def test_cross_field_mixing_raises(self):
        with pytest.raises(TypeError):
            bn.Fr(5) + bn.Fq(7)
        with pytest.raises(TypeError):
            bn.Fq(bls.Fq(123))
        with pytest.raises(TypeError):
            bls.Fq(1) * bn.Fq(1)

    def test_eq_against_foreign_types(self):
        assert bn.Fq(1) != None  # noqa: E711
        assert not (bn.Fr(1) == bn.Fq(1))
        assert bn.Fq(1) in [None, bn.Fq(1)]

    def test_spec_mirrors_reference(self):
        from spectre_tpu import spec
        # values from /root/reference/eth-types/src/spec.rs
        assert spec.MINIMAL.execution_state_root_index == 9
        assert spec.MAINNET.execution_state_root_index == 25
        assert spec.MAINNET.execution_state_root_depth == 4
        assert spec.MAINNET.sync_committee_pubkeys_root_index == 110
        assert spec.MAINNET.sync_committee_pubkeys_depth == 6
        assert spec.MAINNET.dst == spec.DST

    def test_lazy_derived_constants(self):
        assert bls.H2 * bls.R == bls.N2
        assert bls.DST_G2 == bls.DST if hasattr(bls, "DST") else True

"""MXU-native NTT kernel (SPECTRE_NTT_KERNEL) and the fused quotient
vanishing-inverse (SPECTRE_QUOTIENT_FUSED_VINV).

The contract mirrors the NTT-mode suite: the DFT-matmul short-transform
body is the SAME transform as the butterfly stages in a different work
shape — byte-identical outputs, byte-identical proofs. The fused
vanishing-inverse likewise: same mont_mul, one fewer full-width pass, the
pass count pinned STRUCTURALLY (an op-count assertion, not a timing)."""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from spectre_tpu.fields import bn254 as bn
from spectre_tpu.ops import field_ops as F, limbs as L, ntt as NTT

R = bn.R

# (mode, kernel): the kernel knob only has effect inside fourstep's short
# row/column transforms; radix2 ignores it (resolved to "stages")
VARIANTS = [("radix2", "stages"), ("fourstep", "stages"),
            ("fourstep", "matmul")]


def _poly(n, seed=23):
    return [(i * 2654435761 + seed) % R for i in range(n)]


def _mont(vals):
    return jnp.asarray(F.fr_ctx().encode_np(vals))


class TestKernelByteIdentity:
    """{radix2, fourstep x stages, fourstep x matmul} x {ntt, intt,
    coset_lde_std}: identical BYTES, not merely equal values."""

    @pytest.mark.parametrize("k", [6, 10, 12])
    def test_ntt_bytes(self, k):
        omega = bn.fr_root_of_unity(k)
        a = _mont(_poly(1 << k))
        outs = [np.asarray(NTT.ntt(a, omega, mode=m, kernel=kn))
                for m, kn in VARIANTS]
        for got, (m, kn) in zip(outs[1:], VARIANTS[1:]):
            assert np.array_equal(outs[0], got), (k, m, kn)

    @pytest.mark.parametrize("k", [6, 10, 12])
    def test_intt_bytes(self, k):
        omega = bn.fr_root_of_unity(k)
        a = _mont(_poly(1 << k, seed=5))
        outs = [np.asarray(NTT.intt(a, omega, mode=m, kernel=kn))
                for m, kn in VARIANTS]
        for got, (m, kn) in zip(outs[1:], VARIANTS[1:]):
            assert np.array_equal(outs[0], got), (k, m, kn)

    @pytest.mark.parametrize("k", [6, 10, 12])
    def test_coset_lde_std_bytes(self, k):
        omega = bn.fr_root_of_unity(k)
        a_std = jnp.asarray(L.ints_to_limbs16(_poly(1 << k, seed=9)))
        outs = [np.asarray(NTT.coset_lde_std(a_std, omega, 7, mode=m,
                                             kernel=kn))
                for m, kn in VARIANTS]
        for got, (m, kn) in zip(outs[1:], VARIANTS[1:]):
            assert np.array_equal(outs[0], got), (k, m, kn)

    def test_matmul_matches_host_oracle(self):
        from spectre_tpu.native import host
        k = 6
        omega = bn.fr_root_of_unity(k)
        vals = _poly(1 << k, seed=31)
        want = host.limbs_to_ints(
            host.fr_ntt(np.array(host.ints_to_limbs(vals)), omega))
        res = NTT.ntt(_mont(vals), omega, mode="fourstep", kernel="matmul")
        assert F.fr_ctx().decode(res) == want


class TestKernelDispatch:
    def test_env_kernel_dispatch(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_NTT_KERNEL", "matmul")
        assert NTT.ntt_kernel() == "matmul"
        monkeypatch.setenv("SPECTRE_NTT_KERNEL", "bogus")
        with pytest.raises(ValueError):
            NTT.ntt_kernel()

    def test_radix2_ignores_kernel_knob(self):
        # the kernel names fourstep's short-transform body; radix2 resolves
        # to "stages" so trace-cache keys stay stable under the env knob
        assert NTT._resolve_kernel("matmul", "radix2") == "stages"
        assert NTT._resolve_kernel("matmul", "fourstep") == "matmul"
        assert NTT._resolve_kernel(None, "fourstep") == NTT.ntt_kernel()

    def test_length_cap_falls_back_to_stages(self, monkeypatch):
        # beyond _MATMUL_MAX_LOGN the exactness bound (int32 columns,
        # single-REDC u < 2p) no longer holds: _short_transform must route
        # to the butterfly stages, never the matmul body. Routing is the
        # whole contract here — the fallback IS _ntt_stages, whose output
        # the byte-identity matrix already pins — so assert the call
        # pattern, not (tautological) output bytes at the big length.
        calls = []
        orig = NTT._ntt_dft_matmul
        monkeypatch.setattr(
            NTT, "_ntt_dft_matmul",
            lambda a, logn, omega: calls.append(logn) or orig(a, logn, omega))
        small = _mont(_poly(1 << 4, seed=3))
        out = NTT._short_transform(small, 4, bn.fr_root_of_unity(4), "matmul")
        assert calls == [4]
        assert np.array_equal(
            np.asarray(out),
            np.asarray(NTT._ntt_stages(small, 4, bn.fr_root_of_unity(4))))
        # over the cap: stages must be chosen — recorder stands in for the
        # (expensive) transform so the routing check is compute-free
        stage_calls = []
        monkeypatch.setattr(
            NTT, "_ntt_stages",
            lambda x, logn, omega, scale=None:
                stage_calls.append(logn) or x)
        logn = NTT._MATMUL_MAX_LOGN + 1
        a = _mont(_poly(1 << logn, seed=3))
        back = NTT._short_transform(a, logn, bn.fr_root_of_unity(logn),
                                    "matmul")
        assert back is a and stage_calls == [logn]
        assert calls == [4]  # unchanged: the matmul body was never entered


def _tiny_circuit():
    """The k=7 gate+lookup shape shared with test_ntt_modes/test_plonk."""
    from spectre_tpu.plonk.constraint_system import Assignment, CircuitConfig

    k = 7
    cfg = CircuitConfig(k=k, num_advice=1, num_lookup_advice=1,
                        num_fixed=1, lookup_bits=4)
    n = cfg.n
    x_w, y_w = 7, 3
    out = x_w + x_w * y_w
    advice = [[0] * n for _ in range(cfg.num_advice)]
    advice[0][0], advice[0][1], advice[0][2], advice[0][3] = \
        x_w, x_w, y_w, out
    advice[0][4] = 5
    selectors = [[0] * n for _ in range(cfg.num_advice)]
    selectors[0][0] = 1
    lookup = [[0] * n for _ in range(cfg.num_lookup_advice)]
    lookup[0][0] = x_w
    fixed = [[0] * n for _ in range(cfg.num_fixed)]
    fixed[0][0] = 5
    copies = [
        ((cfg.col_instance(0), 0), (cfg.col_gate_advice(0), 3)),
        ((cfg.col_fixed(0), 0), (cfg.col_gate_advice(0), 4)),
        ((cfg.col_gate_advice(0), 0), (cfg.col_lookup_advice(0), 0)),
    ]
    asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
    return cfg, asg, fixed, selectors, copies, [[out]]


def _seeded():
    r = random.Random(0x177E57)
    return lambda: r.randrange(R)


class TestKernelProofBytes:
    """The kernel-knob + fused-vinv correctness gate, mirroring
    TestNttModeProofBytes: stages and matmul must yield BYTE-IDENTICAL
    proofs through the device backend under seeded blinding, and switching
    off SPECTRE_QUOTIENT_FUSED_VINV must change the mul-pass COUNT (by
    exactly one) but never a proof byte. One shared pk: keygen/prove NTT
    equality across kernels is already pinned value-level by the
    byte-identity matrix above, so the expensive keygen runs once.

    slow-marked: ~4 min of prove wall-clock on the 1-core box — runs in
    `make test` (no marker filter), stays out of the 870s tier-1 window
    like test_integrity's heavy drills."""

    @pytest.mark.slow
    def test_proof_bytes_across_kernels_and_fused_vinv(self, monkeypatch):
        from spectre_tpu.plonk import backend as B
        from spectre_tpu.plonk import quotient_device as QD
        from spectre_tpu.plonk.keygen import keygen
        from spectre_tpu.plonk.prover import prove
        from spectre_tpu.plonk.srs import SRS
        from spectre_tpu.plonk.verifier import verify

        cfg, asg, fixed, selectors, copies, instance = _tiny_circuit()
        srs = SRS.unsafe_setup(cfg.k)
        bk = B.get_backend("tpu")

        counts = {"mul": 0}
        orig_helpers = QD._helpers

        def counting_helpers():
            h = dict(orig_helpers())
            real = h["mul"]

            def mul(a, b):
                counts["mul"] += 1
                return real(a, b)

            h["mul"] = mul
            return h

        monkeypatch.setattr(QD, "_helpers", counting_helpers)
        # the explicit path's lazy vinv tensor must rebuild per run, not
        # leak between the two env settings
        monkeypatch.setattr(QD, "_static_cache", {})

        monkeypatch.setenv("SPECTRE_NTT_MODE", "fourstep")
        pk = keygen(srs, cfg, fixed, selectors, copies, bk)
        proofs, muls = {}, {}
        for kern, fused in (("stages", "1"), ("matmul", "1"),
                            ("stages", "0")):
            monkeypatch.setenv("SPECTRE_NTT_KERNEL", kern)
            monkeypatch.setenv("SPECTRE_QUOTIENT_FUSED_VINV", fused)
            counts["mul"] = 0
            proofs[kern, fused] = prove(pk, srs, asg, bk,
                                        blinding_rng=_seeded())
            muls[kern, fused] = counts["mul"]
            assert verify(pk.vk, srs, instance, proofs[kern, fused]), \
                (kern, fused)
        assert proofs["stages", "1"] == proofs["matmul", "1"], \
            "SPECTRE_NTT_KERNEL changed proof bytes (kernels must be " \
            "identical)"
        assert proofs["stages", "1"] == proofs["stages", "0"], \
            "fused vanishing-inverse changed proof bytes"
        # the structural pin: folding the inverse into the iNTT's stage-0
        # table removes EXACTLY ONE full-width elementwise mont_mul
        # dispatch per quotient
        assert muls["stages", "0"] == muls["stages", "1"] + 1, muls


class TestFusedVinvQuotient:
    """SPECTRE_QUOTIENT_FUSED_VINV: the vanishing-inverse folded into
    stage 0 of the inverse coset NTT vs the explicit [4n, 16] pre-multiply,
    checked at the kernel level (the proof-level gate rides
    TestKernelProofBytes)."""

    def test_vinv_table_matches_explicit(self):
        from spectre_tpu.plonk.domain import COSET_GEN, Domain
        dom = Domain(4)
        vals = dom.vanishing_inv_period_vals()
        # the period tuple IS the extended-domain inverse, tiled
        from spectre_tpu.plonk import backend as B
        want = dom.vanishing_inv_on_extended()
        tiled = [vals[i % len(vals)] for i in range(dom.n_ext)]
        assert np.array_equal(B.to_arr(tiled), want)
        # fused entry == explicit multiply-then-transform, byte-for-byte
        a = _mont(_poly(dom.n_ext, seed=41))
        vtab = jnp.asarray(F.fr_ctx().encode(
            [vals[i % len(vals)] for i in range(dom.n_ext)]))
        explicit = NTT.coset_intt_std(
            F.mont_mul(F.fr_ctx(), a, vtab), dom.omega_ext, COSET_GEN)
        fused = NTT.coset_intt_std_vinv(a, dom.omega_ext, COSET_GEN, vals)
        assert np.array_equal(np.asarray(explicit), np.asarray(fused))

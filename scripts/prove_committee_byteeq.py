#!/usr/bin/env python
"""TPU-backend REAL prove at reference scale, byte-identical vs CpuBackend.

VERDICT r3 item 4: committee-update 512 (k=18) proved through BOTH backends
with the SAME seeded blinding — the proofs must be byte-equal (the backends
differ in where the math runs, never in what they compute). Phase timers on;
writes the record to build/committee_byteeq_<spec>_<k>.json.

Phases (r5 lesson: the axon tunnel wedges LONG-LIVED connections mid-bulk-
transfer — a keygen routed through the ambient device platform blocked in
tcp_recvmsg for 30+ min while fresh connections worked fine):

  cpu  — JAX pinned to CPU: keygen (pk lands in the params cache) + the
         CpuBackend prove; writes the proof bytes + record.
  tpu  — ambient device platform, FRESH process/connection: loads the pk
         from cache, proves on TpuBackend (device quotient on), compares
         byte-for-byte against the cpu phase's proof.
  all  — both in-process (the original single-process flow; only sensible
         when the ambient platform is already CPU).

Run:
  python scripts/prove_committee_byteeq.py [spec] [k] [--phase cpu|tpu|all]
"""
import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("SPECTRE_TRACE", "1")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("spec", nargs="?", default="testnet")
    ap.add_argument("k", nargs="?", type=int, default=18)
    ap.add_argument("--phase", choices=("cpu", "tpu", "all"), default="all")
    opts = ap.parse_args()
    phase, spec_name, k = opts.phase, opts.spec, opts.k

    import jax
    if phase == "cpu" or (phase == "all" and
                          os.environ.get("JAX_PLATFORMS", "axon")
                          in ("", "cpu", "axon")):
        # The box ambient is JAX_PLATFORMS=axon (sitecustomize) — the
        # historically wedged tunnel. The cpu phase pins CPU UNCONDITIONALLY
        # (the flag IS the operator intent; ambient axon is the box default,
        # not a request); 'all' pins CPU unless the operator explicitly
        # named a non-axon device platform. The tpu phase keeps the ambient
        # platform — pinning CPU there would record trivially-true "byte
        # equality" that never touched the device.
        jax.config.update("jax_platforms", "cpu")
    if phase == "tpu":
        # sitecustomize's axon plugin registration is itself flaky: when it
        # fails, JAX_PLATFORMS may still name 'axon' (now an unknown
        # backend, so default_backend() raises), or auto-choice may silently
        # land on CPU — either way the "tpu" prove would be meaningless.
        # Fall back to auto-choice, then REQUIRE a device: a CPU-vs-CPU byte
        # comparison must never masquerade as hardware evidence.
        try:
            backend = jax.default_backend()
        except RuntimeError:
            jax.config.update("jax_platforms", "")
            backend = jax.default_backend()
        assert backend != "cpu", \
            "tpu phase resolved to the CPU platform (axon plugin absent or " \
            "tunnel down) — rerun when a device is reachable"
    from spectre_tpu.plonk.backend import setup_compile_cache
    setup_compile_cache()

    from spectre_tpu import spec as S
    from spectre_tpu.models import CommitteeUpdateCircuit
    from spectre_tpu.models.app_circuit import BUILD_DIR
    from spectre_tpu.plonk import backend as B
    from spectre_tpu.plonk.prover import prove as plonk_prove
    from spectre_tpu.plonk.srs import SRS
    from spectre_tpu.witness.rotation import default_committee_update_args

    spec = S.SPECS[spec_name]
    cpu_proof_path = os.path.join(BUILD_DIR,
                                  f"committee_byteeq_{spec.name}_{k}.cpu.proof")
    record_path = os.path.join(BUILD_DIR,
                               f"committee_byteeq_{spec.name}_{k}.json")
    if phase == "tpu":
        assert os.path.exists(cpu_proof_path), \
            "run --phase cpu first (the byte-equality oracle)"

    t0 = time.time()
    args = default_committee_update_args(spec)
    print(f"[{time.time()-t0:7.1f}s] fixture ({spec.sync_committee_size} keys)",
          flush=True)
    srs = SRS.load_or_setup(k)
    pk = CommitteeUpdateCircuit.create_pk(srs, spec, k, args)
    print(f"[{time.time()-t0:7.1f}s] pk ready", flush=True)
    ctx = CommitteeUpdateCircuit.build_context(args, spec)
    asg = ctx.assignment(pk.vk.config)
    print(f"[{time.time()-t0:7.1f}s] assignment ready", flush=True)

    record = {"spec": spec.name, "k": k}
    if os.path.exists(record_path):
        with open(record_path) as f:
            record.update(json.load(f))
    # stale comparison results must never survive into this run's record: a
    # fresh cpu oracle invalidates any earlier comparison, and a repeated
    # tpu phase must not inherit a prior run's byte_identical:true — the
    # pre-comparison write below would otherwise persist it even when THIS
    # run's device proof diverges (ADVICE r5 medium). byte_identical is
    # re-set only after the compare passes.
    for stale in ("byte_identical", "tpu_prove_s", "tpu_platform"):
        record.pop(stale, None)

    backends = {"cpu": ("cpu",), "tpu": ("tpu",), "all": ("cpu", "tpu")}[phase]
    proofs = {}
    for name in backends:
        bk = B.get_backend(name)
        rng = random.Random(0xBEEF)
        t = time.time()
        proofs[name] = plonk_prove(pk, srs, asg, bk,
                                   blinding_rng=lambda: rng.randrange(B.R))
        record[f"{name}_prove_s"] = round(time.time() - t, 1)
        print(f"[{time.time()-t0:7.1f}s] {name} prove: "
              f"{record[f'{name}_prove_s']}s, {len(proofs[name])} bytes",
              flush=True)

    if phase in ("cpu", "all"):
        with open(cpu_proof_path, "wb") as f:
            f.write(proofs["cpu"])
    if phase == "tpu":
        record["tpu_platform"] = jax.default_backend()
        # persist the device proof and the timings BEFORE the comparison: a
        # divergence — the event this script exists to detect — must leave
        # both artifacts on disk, not die with a bare assert
        with open(cpu_proof_path[:-len(".cpu.proof")] + ".tpu.proof",
                  "wb") as f:
            f.write(proofs["tpu"])
        with open(record_path, "w") as f:
            json.dump(record, f, indent=1)
        with open(cpu_proof_path, "rb") as f:
            proofs["cpu"] = f.read()

    if "cpu" in proofs and "tpu" in proofs:
        assert proofs["cpu"] == proofs["tpu"], \
            "backend proofs DIVERGE at reference scale " \
            f"(artifacts: {cpu_proof_path}[.tpu.proof])"
        record["byte_identical"] = True
    record["proof_bytes"] = len(proofs[backends[-1]])
    inst = CommitteeUpdateCircuit.get_instances(args, spec)
    ok = CommitteeUpdateCircuit.verify(pk.vk, srs, inst, proofs[backends[-1]])
    assert ok, "proof does not verify"
    record["verifies"] = True
    with open(record_path, "w") as f:
        json.dump(record, f, indent=1)
    tag = ("BYTE-IDENTICAL + verifies" if record.get("byte_identical")
           else f"phase {phase} done, verifies")
    print(f"[{time.time()-t0:7.1f}s] {tag} -> {record_path}", flush=True)


if __name__ == "__main__":
    main()

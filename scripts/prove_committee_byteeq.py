#!/usr/bin/env python
"""TPU-backend REAL prove at reference scale, byte-identical vs CpuBackend.

VERDICT r3 item 4: committee-update 512 (k=18) proved through BOTH backends
with the SAME seeded blinding — the proofs must be byte-equal (the backends
differ in where the math runs, never in what they compute). Phase timers on;
writes the record to build/committee_byteeq_<spec>_<k>.json.

Run: JAX_PLATFORMS=cpu SPECTRE_TRACE=1 python scripts/prove_committee_byteeq.py [spec] [k]
"""
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SPECTRE_TRACE", "1")


def main():
    import jax
    if "JAX_PLATFORMS" not in os.environ or \
            os.environ["JAX_PLATFORMS"] == "cpu":
        # sitecustomize pins the (historically wedged) axon platform; pin CPU
        # unless the operator explicitly requested a device platform
        jax.config.update("jax_platforms", "cpu")
    from spectre_tpu.plonk.backend import setup_compile_cache
    setup_compile_cache()

    from spectre_tpu import spec as S
    from spectre_tpu.models import CommitteeUpdateCircuit
    from spectre_tpu.models.app_circuit import BUILD_DIR
    from spectre_tpu.plonk import backend as B
    from spectre_tpu.plonk.prover import prove as plonk_prove
    from spectre_tpu.plonk.srs import SRS
    from spectre_tpu.witness.rotation import default_committee_update_args

    spec = S.SPECS[sys.argv[1] if len(sys.argv) > 1 else "testnet"]
    k = int(sys.argv[2]) if len(sys.argv) > 2 else 18
    t0 = time.time()
    args = default_committee_update_args(spec)
    print(f"[{time.time()-t0:7.1f}s] fixture ({spec.sync_committee_size} keys)",
          flush=True)
    srs = SRS.load_or_setup(k)
    pk = CommitteeUpdateCircuit.create_pk(srs, spec, k, args)
    print(f"[{time.time()-t0:7.1f}s] pk ready", flush=True)
    ctx = CommitteeUpdateCircuit.build_context(args, spec)
    asg = ctx.assignment(pk.vk.config)
    print(f"[{time.time()-t0:7.1f}s] assignment ready", flush=True)

    record = {"spec": spec.name, "k": k}
    proofs = {}
    for name in ("cpu", "tpu"):
        bk = B.get_backend(name)
        rng = random.Random(0xBEEF)
        t = time.time()
        proofs[name] = plonk_prove(pk, srs, asg, bk,
                                   blinding_rng=lambda: rng.randrange(B.R))
        record[f"{name}_prove_s"] = round(time.time() - t, 1)
        print(f"[{time.time()-t0:7.1f}s] {name} prove: "
              f"{record[f'{name}_prove_s']}s, {len(proofs[name])} bytes",
              flush=True)
    assert proofs["cpu"] == proofs["tpu"], \
        "backend proofs DIVERGE at reference scale"
    record["byte_identical"] = True
    record["proof_bytes"] = len(proofs["cpu"])
    inst = CommitteeUpdateCircuit.get_instances(args, spec)
    ok = CommitteeUpdateCircuit.verify(pk.vk, srs, inst, proofs["cpu"])
    assert ok, "proof does not verify"
    record["verifies"] = True
    out = os.path.join(BUILD_DIR, f"committee_byteeq_{spec.name}_{k}.json")
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
    print(f"[{time.time()-t0:7.1f}s] BYTE-IDENTICAL + verifies -> {out}",
          flush=True)


if __name__ == "__main__":
    main()

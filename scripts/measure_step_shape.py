#!/usr/bin/env python
"""Measure the slimmed StepCircuit's cell budget + auto_config column counts.

Run: python scripts/measure_step_shape.py [tiny|minimal|testnet]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from spectre_tpu.models import StepCircuit
from spectre_tpu.spec import MINIMAL, TESTNET, TINY
from spectre_tpu.witness.step import default_sync_step_args

SPECS = {"tiny": TINY, "minimal": MINIMAL, "testnet": TESTNET}


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "tiny"
    # optional lookup_bits override: the RANGE-CHECK DECOMPOSITION inside
    # the circuit depends on it, so the context must be rebuilt per value
    # (this is how the lb=16/18 shapes were measured; reference pins lb=20
    # at k=21, `config/sync_step_testnet.json`)
    if len(sys.argv) > 2:
        StepCircuit.default_lookup_bits = int(sys.argv[2])
    spec = SPECS[which]
    args = default_sync_step_args(spec)
    t0 = time.time()
    ctx = StepCircuit.build_context(args, spec)
    dt = time.time() - t0
    print(f"lookup_bits={StepCircuit.default_lookup_bits}")
    st = ctx.stats()
    print(f"spec={which} build={dt:.1f}s")
    print(f"advice_cells={st['advice_cells']:,}")
    print(f"lookup_cells={st['lookup_cells']}")
    print(f"copies={st['copies']:,} constants={st['constants']:,}")
    print(f"sha_slots={len(ctx.sha_slots)}")
    for k in range(17, 23):
        try:
            cfg = ctx.auto_config(k=k, lookup_bits=StepCircuit.default_lookup_bits)
        except AssertionError as e:
            print(f"k={k}: {e}")
            continue
        print(f"k={k}: advice={cfg.num_advice} lookup_advice={cfg.num_lookup_advice} "
              f"tables={cfg.lookup_tables} fixed={cfg.num_fixed} "
              f"perm_cols={cfg.num_perm_columns}")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Per-component advice/lookup cell budget of the StepCircuit building blocks.

Run: python scripts/profile_cells.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from spectre_tpu.builder import Context, GateChip, RangeChip
from spectre_tpu.builder.fp_chip import EccChip, FpChip
from spectre_tpu.builder.fp2_chip import Fp2Chip, G2Chip
from spectre_tpu.builder.fp12_chip import Fp12Chip
from spectre_tpu.builder.hash_to_curve_chip import HashToCurveChip
from spectre_tpu.builder.pairing_chip import PairingChip
from spectre_tpu.builder.sha256_chip import Sha256Chip
from spectre_tpu.fields import bls12_381 as bls


def cost(label, fn):
    ctx = Context()
    gate = GateChip()
    rng = RangeChip(16, gate)
    fp = FpChip(rng)
    fp2 = Fp2Chip(fp)
    ecc = EccChip(fp)
    g2 = G2Chip(fp2)
    fp12 = Fp12Chip(fp2)
    pairing = PairingChip(fp12)
    sha_nib = Sha256Chip(gate)
    h2c = HashToCurveChip(pairing, sha_nib)
    t0 = time.time()
    fn(ctx, dict(gate=gate, rng=rng, fp=fp, fp2=fp2, ecc=ecc, g2=g2,
                 fp12=fp12, pairing=pairing, sha=sha_nib, h2c=h2c))
    dt = time.time() - t0
    st = ctx.stats()
    lkp = sum(st["lookup_cells"].values())
    print(f"{label:42s} adv={st['advice_cells']:>9,} lkp={lkp:>9,} "
          f"copies={st['copies']:>8,}  {dt:5.1f}s")
    return st["advice_cells"]


G1 = bls.G1_GEN
G2pt = bls.G2_GEN
P2 = bls.g2_curve.mul(G2pt, 7)
P1 = bls.g1_curve.mul(G1, 5)


def main():
    which = sys.argv[1:] or ["fp", "g1", "g2", "fp12", "miller",
                             "pairing2", "subgroup", "h2c"]
    C = {}
    if "fp" in which:
        cost("fp.mul x100", lambda c, k: [
            k["fp"].mul(c, k["fp"].load(c, 12345), k["fp"].load(c, 6789))
            for _ in range(100)])
    if "g1" in which:
        cost("ecc.load_point (on-curve)", lambda c, k: k["ecc"].load_point(c, P1))
        cost("ecc.add_unequal_lazy", lambda c, k: k["ecc"].add_unequal_lazy(
            c, k["ecc"].load_point(c, P1), k["ecc"].load_point(c, G1)))
    if "g2" in which:
        cost("g2.load_point", lambda c, k: k["g2"].load_point(c, P2))
        cost("g2.add_unequal", lambda c, k: k["g2"].add_unequal(
            c, k["g2"].load_point(c, P2), k["g2"].load_point(c, G2pt)))
        cost("g2.double", lambda c, k: k["g2"].double(c, k["g2"].load_point(c, P2)))
    if "fp12" in which:
        def f12(c, k):
            a = k["fp12"].load(c, bls.Fq12([i + 1 for i in range(12)]))
            b = k["fp12"].load(c, bls.Fq12([2 * i + 3 for i in range(12)]))
            k["fp12"].mul(c, a, b)
        cost("fp12.mul", f12)

        def f12sq(c, k):
            a = k["fp12"].load(c, bls.Fq12([i + 1 for i in range(12)]))
            k["fp12"].square(c, a)
        cost("fp12.square", f12sq)
    if "miller" in which:
        def ml(c, k):
            p = k["ecc"].load_point(c, P1)
            q = k["g2"].load_point(c, P2)
            k["pairing"].multi_miller_loop(c, [(p, q)])
        cost("miller_loop 1 pair", ml)
    if "pairing2" in which:
        def p2(c, k):
            p = k["ecc"].load_point(c, P1)
            np_ = k["ecc"].load_point(c, bls.g1_curve.neg(P1))
            q = k["g2"].load_point(c, P2)
            s = bls.g2_curve.mul(P2, 1)  # e(P,Q)*e(-P,Q) == 1
            q2 = k["g2"].load_point(c, s)
            k["pairing"].assert_pairing_product_one(c, [(p, q), (np_, q2)])
        cost("pairing product (2 pairs + final exp)", p2)
    if "subgroup" in which:
        def sg(c, k):
            q = k["g2"].load_point(c, P2 if False else G2pt)
            k["pairing"].assert_g2_subgroup(c, q)
        cost("g2 subgroup check", sg)
    if "h2c" in which:
        def h(c, k):
            msg = [c.load_witness(i & 0xFF) for i in range(32)]
            for m in msg:
                k["sha"]._range_bits(c, m, 8)
            k["h2c"].hash_to_g2(c, msg, b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_")
        cost("hash_to_g2 (full)", h)


if __name__ == "__main__":
    main()

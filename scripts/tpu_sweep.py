#!/usr/bin/env python
"""Production-size TPU kernel sweep (MSM + NTT + field throughput).

bench.py's headline is MSM 2^16 — but the flagship prove's MSMs are
2^21 (k=21 commitments) and its quotient NTTs are 2^21..2^23. TPU
amortization improves with size (r1: NTT 2^20 was 3.9x CPU while MSM 2^16
was ~1x), so the production-relevant comparison is the sweep, not the
point. Runs each size on the ambient device AND the native C++ single-
thread baseline, writes build/tpu_sweep.json.

Usage: python scripts/tpu_sweep.py [--msm 16,18,20] [--ntt 20,22] [--quick]
Every device phase is a subprocess with a deadline (tunnel-wedge-proof,
same pattern as bench.py).
"""
import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "build", "tpu_sweep.json")
T0 = time.time()


def log(msg):
    print(f"[{time.time()-T0:7.1f}s] {msg}", flush=True)


def child_msm(logn: int, c: int, out_path: str):
    import jax
    from spectre_tpu.plonk.backend import setup_compile_cache
    setup_compile_cache()
    import jax.numpy as jnp
    import numpy as np

    from spectre_tpu.ops import field_ops as F, limbs as L, msm as MSM
    sys.path.insert(0, os.path.join(REPO))
    from bench import bench_inputs

    pts64, sc64 = bench_inputs(logn)
    ctxq = F.fq_ctx()
    x16 = L.u64limbs_to_u16limbs(pts64[:, :4])
    y16 = L.u64limbs_to_u16limbs(pts64[:, 4:])
    to_mont = jax.jit(lambda v: F.to_mont(ctxq, v))
    xm, ym = to_mont(jnp.asarray(x16)), to_mont(jnp.asarray(y16))
    one = jnp.broadcast_to(jnp.asarray(ctxq.one_mont),
                           (1 << logn, F.NLIMBS))
    pts = jnp.stack([xm, ym, one], axis=1)
    sc16 = jnp.asarray(L.u64limbs_to_u16limbs(sc64))

    def run():
        return np.asarray(
            MSM.combine_windows(MSM.msm_windows(pts, sc16, c), c))

    run()                      # compile + warm
    dt = float("inf")
    for _ in range(3):
        t0 = time.time()
        run()
        dt = min(dt, time.time() - t0)
    with open(out_path, "w") as f:
        json.dump({"seconds": dt, "points_per_s": (1 << logn) / dt,
                   "backend": jax.default_backend(), "c": c}, f)


def child_ntt(logn: int, out_path: str):
    import jax
    from spectre_tpu.plonk.backend import setup_compile_cache
    setup_compile_cache()
    import jax.numpy as jnp
    import numpy as np

    from spectre_tpu.fields import bn254 as bn
    from spectre_tpu.ops import field_ops as F, ntt as NTT
    from spectre_tpu.plonk.domain import Domain

    omega = Domain(logn).omega
    fctx = F.fr_ctx()
    vals = [(i * 2654435761 + 17) % bn.R for i in range(1 << logn)]
    arr = jnp.asarray(fctx.encode_np(vals))

    def run():
        return np.asarray(NTT.ntt(arr, omega))

    run()
    dt = float("inf")
    for _ in range(3):
        t0 = time.time()
        run()
        dt = min(dt, time.time() - t0)
    with open(out_path, "w") as f:
        json.dump({"seconds": dt, "backend": jax.default_backend()}, f)


def child_mont(logn: int, out_path: str):
    import jax
    from spectre_tpu.plonk.backend import setup_compile_cache
    setup_compile_cache()
    import jax.numpy as jnp
    import numpy as np

    from spectre_tpu.fields import bn254 as bn
    from spectre_tpu.ops import field_ops as F

    n = 1 << logn
    ctx = F.fq_ctx()
    a = [(i * 48271 + 11) % bn.P for i in range(n)]
    b = [(i * 69621 + 7) % bn.P for i in range(n)]
    am = jnp.asarray(ctx.encode_np(a))
    bm = jnp.asarray(ctx.encode_np(b))
    mul = jax.jit(lambda x, y: F.mont_mul(ctx, x, y))

    def run():
        return np.asarray(mul(am, bm))

    run()
    dt = float("inf")
    for _ in range(3):
        t0 = time.time()
        run()
        dt = min(dt, time.time() - t0)
    with open(out_path, "w") as f:
        json.dump({"seconds": dt, "muls_per_s": n / dt,
                   "backend": jax.default_backend()}, f)


def run_child(kind: str, timeout: float, **kw):
    import tempfile
    fd, out = tempfile.mkstemp(suffix=".json")
    os.close(fd)
    env = dict(os.environ, SWEEP_CHILD=kind, SWEEP_OUT=out,
               SWEEP_KW=json.dumps(kw))
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, cwd=REPO, timeout=timeout,
                           capture_output=True, text=True)
        if r.returncode == 0 and os.path.getsize(out):
            with open(out) as f:
                return json.load(f)
        return {"error": (r.stderr or "")[-400:]}
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout}s"}
    finally:
        try:
            os.unlink(out)
        except OSError:
            pass


def native_msm_baseline(logn: int) -> float:
    from bench import bench_inputs
    from spectre_tpu.native import host
    pts64, sc64 = bench_inputs(logn)
    dt = float("inf")
    for _ in range(3):
        t0 = time.time()
        host.g1_msm(pts64, sc64)
        dt = min(dt, time.time() - t0)
    return dt


def native_ntt_baseline(logn: int) -> float:
    from spectre_tpu.fields import bn254 as bn
    from spectre_tpu.native import host
    from spectre_tpu.plonk.domain import Domain
    omega = Domain(logn).omega
    vals = host.ints_to_limbs([(i * 2654435761 + 17) % bn.R
                               for i in range(1 << logn)])
    dt = float("inf")
    for _ in range(3):
        t0 = time.time()
        host.fr_ntt(vals, omega)     # in place; timing unaffected by content
        dt = min(dt, time.time() - t0)
    return dt


def main():
    kind = os.environ.get("SWEEP_CHILD")
    if kind:
        kw = json.loads(os.environ["SWEEP_KW"])
        {"msm": child_msm, "ntt": child_ntt,
         "mont": child_mont}[kind](out_path=os.environ["SWEEP_OUT"], **kw)
        return

    ap = argparse.ArgumentParser()
    ap.add_argument("--msm", default="16,18,20")
    ap.add_argument("--ntt", default="20,22")
    ap.add_argument("--mont", default="20")
    ap.add_argument("--quick", action="store_true")
    opts = ap.parse_args()
    if opts.quick:
        opts.msm, opts.ntt, opts.mont = "16", "20", "20"

    res = {"started_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "msm": {}, "ntt": {}, "mont": {}}

    def save():
        with open(OUT, "w") as f:
            json.dump(res, f, indent=1)

    for logn in [int(v) for v in opts.msm.split(",") if v]:
        c = 13 if logn >= 18 else 10
        dev = run_child("msm", timeout=1800, logn=logn, c=c)
        log(f"msm 2^{logn} device: {dev}")
        cpu_dt = native_msm_baseline(logn)
        entry = {"device": dev, "cpu_native_s": round(cpu_dt, 3)}
        if "seconds" in dev:
            entry["speedup_vs_1core"] = round(cpu_dt / dev["seconds"], 2)
        res["msm"][f"2^{logn}"] = entry
        save()
        log(f"msm 2^{logn}: cpu {cpu_dt:.2f}s; {entry.get('speedup_vs_1core')}x")

    for logn in [int(v) for v in opts.ntt.split(",") if v]:
        dev = run_child("ntt", timeout=1800, logn=logn)
        log(f"ntt 2^{logn} device: {dev}")
        cpu_dt = native_ntt_baseline(logn)
        entry = {"device": dev, "cpu_native_s": round(cpu_dt, 3)}
        if "seconds" in dev:
            entry["speedup_vs_1core"] = round(cpu_dt / dev["seconds"], 2)
        res["ntt"][f"2^{logn}"] = entry
        save()
        log(f"ntt 2^{logn}: cpu {cpu_dt:.2f}s; {entry.get('speedup_vs_1core')}x")

    for logn in [int(v) for v in opts.mont.split(",") if v]:
        dev = run_child("mont", timeout=1200, logn=logn)
        res["mont"][f"2^{logn}"] = {"device": dev}
        save()
        log(f"mont 2^{logn}: {dev}")

    res["finished_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    save()
    log(f"DONE -> {OUT}")


if __name__ == "__main__":
    main()

"""Shared driver for the compressed two-stage production flows.

Both production methods (`genEvmProof_SyncStepCompressed` and
`genEvmProof_CommitteeUpdateCompressed`, `prover/src/rpc.rs:46-163`) are the
same pipeline over different inner circuits:

    stage 1: inner app-circuit prove (Poseidon transcript) at --spec/--k
    stage 2: AggregationCircuit outer prove (Keccak transcript) at auto-k
    finish:  calldata + generated Solidity verifier execution + static
             gas / deployed-size estimates

`run_compressed_flow` is that pipeline, parameterized by the inner circuit.
Checkpoints land in build/ so a crashed run resumes (inner/outer proofs are
regenerated only when absent). The per-circuit scripts
(prove_step_compressed.py, prove_committee_compressed.py) are thin arg
wrappers over this module.
"""
import json
import os
import time

T0 = time.time()


def log(msg):
    print(f"[{time.time()-T0:8.1f}s] {msg}", flush=True)


def run_compressed_flow(circuit_cls, default_args_fn, *, spec, k: int,
                        k_agg="auto", k_agg_range=(20, 25),
                        max_agg_cells: float = 90e6, max_agg_advice: int = 12,
                        record_name: str, inner_proof_name: str,
                        outer_proof_name: str, verifier_name: str,
                        contract_name: str, stop_after: str = "all",
                        tamper_byte: int = 37) -> dict:
    """The two-stage flow end-to-end; returns the record dict."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spectre_tpu.plonk.backend import setup_compile_cache
    setup_compile_cache()

    from spectre_tpu.models import AggregationArgs, AggregationCircuit
    from spectre_tpu.models.app_circuit import BUILD_DIR
    from spectre_tpu.plonk.srs import SRS
    from spectre_tpu.plonk.transcript import (KeccakTranscript,
                                              PoseidonTranscript)
    from spectre_tpu.plonk.verifier import verify as plonk_verify

    record_path = os.path.join(BUILD_DIR, record_name)
    record = {"spec": spec.name, f"k_{circuit_cls.name}": k}
    if os.path.exists(record_path):
        with open(record_path) as f:
            record.update(json.load(f))
        # drop pre-refactor schema keys so a resumed record can't carry a
        # stale config next to the live one
        for stale in ("k_step", "step_config", "k_committee",
                      "committee_config"):
            record.pop(stale, None)

    def save_record():
        with open(record_path, "w") as f:
            json.dump(record, f, indent=1)

    args = default_args_fn(spec)
    log(f"fixture ready ({spec.sync_committee_size} pubkeys)")

    # ---- stage 1: inner snark (Poseidon transcript) ----
    srs = SRS.load_or_setup(k)
    log(f"srs k={k}")
    t = time.time()
    pk = circuit_cls.create_pk(srs, spec, k, args)
    record.setdefault("keygen_s", round(time.time() - t, 1))
    cfg = pk.vk.config
    log(f"{circuit_cls.name} pk ready: advice={cfg.num_advice} "
        f"lookup={cfg.num_lookup_advice} sha_slots={cfg.num_sha_slots}")
    record["inner_config"] = {
        "num_advice": cfg.num_advice,
        "num_lookup_advice": cfg.num_lookup_advice,
        "lookup_bits": cfg.lookup_bits, "num_sha_slots": cfg.num_sha_slots}
    save_record()

    proof_path = os.path.join(BUILD_DIR, inner_proof_name)
    inst = circuit_cls.get_instances(args, spec)
    if os.path.exists(proof_path):
        with open(proof_path, "rb") as f:
            proof = f.read()
        log(f"stage-1 proof loaded from cache ({len(proof)} bytes)")
    else:
        t = time.time()
        proof = circuit_cls.prove(pk, srs, args, spec,
                                  transcript=PoseidonTranscript())
        record["stage1_prove_s"] = round(time.time() - t, 1)
        with open(proof_path, "wb") as f:
            f.write(proof)
        log(f"STAGE-1 PROOF: {len(proof)} bytes in "
            f"{record['stage1_prove_s']}s")
    record["stage1_proof_bytes"] = len(proof)
    t = time.time()
    ok = plonk_verify(pk.vk, srs, [inst], proof,
                      transcript_cls=PoseidonTranscript)
    assert ok, "stage-1 proof does not verify"
    record["stage1_verify_s"] = round(time.time() - t, 1)
    log(f"stage-1 verifies ({record['stage1_verify_s']}s)")
    save_record()
    if stop_after == "inner":
        return record

    # ---- stage 2: aggregation over the inner snark ----
    agg_cls = AggregationCircuit.variant(circuit_cls.name)
    agg_args = AggregationArgs(inner_vk=pk.vk, srs=srs,
                               inner_instances=[inst], proof=proof)
    t = time.time()
    ctx = agg_cls.build_context(agg_args, spec)
    st = ctx.stats()
    record["agg_build_s"] = round(time.time() - t, 1)
    record["agg_advice_cells"] = st["advice_cells"]
    record["agg_lookup_cells"] = sum(st["lookup_cells"].values())
    log(f"agg circuit built in {record['agg_build_s']}s: "
        f"{st['advice_cells']:,} advice cells, "
        f"{record['agg_lookup_cells']:,} lookup cells")
    save_record()
    assert st["advice_cells"] <= max_agg_cells, \
        f"aggregation circuit too large ({st['advice_cells']:,} cells)"

    if k_agg == "auto":
        cagg = None
        for k_agg in range(*k_agg_range):
            cagg = ctx.auto_config(k=k_agg,
                                   lookup_bits=agg_cls.default_lookup_bits)
            if cagg.num_advice <= max_agg_advice:
                break
        assert cagg is not None and cagg.num_advice <= max_agg_advice, \
            f"no k in {k_agg_range[0]}..{k_agg_range[1] - 1} meets " \
            f"max_agg_advice={max_agg_advice}" + \
            (f" (k={k_agg} needs {cagg.num_advice} advice)" if cagg else "")
    else:
        k_agg = int(k_agg)
        cagg = ctx.auto_config(k=k_agg,
                               lookup_bits=agg_cls.default_lookup_bits)
    record["k_agg"] = k_agg
    record["agg_config"] = {"num_advice": cagg.num_advice,
                            "num_lookup_advice": cagg.num_lookup_advice}
    log(f"agg k={k_agg}: advice={cagg.num_advice} "
        f"lookup={cagg.num_lookup_advice}")
    save_record()
    if stop_after == "agg-build":
        return record

    srs_agg = SRS.load_or_setup(k_agg)
    log(f"srs k={k_agg}")
    t = time.time()
    agg_pk = agg_cls.create_pk(srs_agg, spec, k_agg, agg_args)
    record.setdefault("agg_keygen_s", round(time.time() - t, 1))
    log("agg pk ready")
    save_record()

    # proof/verifier names may carry a {k_agg} placeholder (auto-k flows)
    oproof_path = os.path.join(BUILD_DIR,
                               outer_proof_name.format(k_agg=k_agg))
    if os.path.exists(oproof_path):
        with open(oproof_path, "rb") as f:
            oproof = f.read()
        with open(oproof_path + ".instances.json") as f:
            stmt = [int(v, 16) for v in json.load(f)["instances"]]
        log(f"stage-2 proof loaded from cache ({len(oproof)} bytes)")
    else:
        stmt = AggregationCircuit.get_instances(agg_args, spec)
        t = time.time()
        oproof = agg_cls.prove(agg_pk, srs_agg, agg_args, spec,
                               transcript=KeccakTranscript())
        record["stage2_prove_s"] = round(time.time() - t, 1)
        with open(oproof_path, "wb") as f:
            f.write(oproof)
        with open(oproof_path + ".instances.json", "w") as f:
            json.dump({"instances": [hex(v) for v in stmt]}, f)
        log(f"STAGE-2 PROOF: {len(oproof)} bytes in "
            f"{record['stage2_prove_s']}s")
    record["stage2_proof_bytes"] = len(oproof)
    t = time.time()
    ok = agg_cls.verify(agg_pk.vk, srs_agg, stmt, oproof,
                        transcript_cls=KeccakTranscript)
    assert ok, "outer proof (incl. deferred pairing) does not verify"
    record["stage2_verify_s"] = round(time.time() - t, 1)
    log(f"stage-2 verifies incl. deferred KZG pairing "
        f"({record['stage2_verify_s']}s)")
    save_record()

    # ---- EVM artifact: calldata + generated verifier + gas model ----
    from spectre_tpu.evm import (encode_calldata, estimate_deployed_size,
                                 estimate_gas, gen_evm_verifier)
    from spectre_tpu.evm.simulator import run_verifier
    calldata = encode_calldata(stmt, oproof)
    record["calldata_bytes"] = len(calldata)
    t = time.time()
    sol = gen_evm_verifier(agg_pk.vk, srs_agg, num_instances=len(stmt),
                           contract_name=contract_name, num_acc_limbs=12)
    sol_path = os.path.join(BUILD_DIR, verifier_name.format(k_agg=k_agg))
    with open(sol_path, "w") as f:
        f.write(sol)
    record["verifier_sol_bytes"] = len(sol)
    log(f"EVM verifier generated: {len(sol)} bytes source")
    ok = run_verifier(sol, stmt, oproof)
    assert ok, "generated Solidity verifier rejected the outer proof"
    bad = bytearray(oproof)
    bad[tamper_byte] ^= 1
    assert not run_verifier(sol, stmt, bytes(bad)), \
        "generated verifier accepted a tampered proof"
    record["evm_verifier_s"] = round(time.time() - t, 1)
    record["evm_verifier_ok"] = True
    g = estimate_gas(sol, calldata=calldata)
    sz = estimate_deployed_size(sol)
    record["gas_estimate"] = {kk: v for kk, v in g.items() if kk != "counts"}
    record["deployed_size_estimate"] = sz
    log(f"gas estimate: {g.get('gas_total', g['gas_execution']):,}; "
        f"deployed ~{sz['deployed_bytes_estimate']:,} B "
        f"[{sz['deployed_size_risk']}]")
    save_record()

    # ---- real EVM: compile the verifier to bytecode, meter the gas ----
    from spectre_tpu.evm.solc import vm_verify
    t = time.time()
    rv = vm_verify(sol, stmt, oproof, tamper_byte=tamper_byte)
    assert rv["ok"], "compiled bytecode verifier rejected the outer proof"
    assert rv["tamper_rejected"], \
        "compiled bytecode verifier accepted a tampered proof"
    record["evm_real"] = {
        "gas_execution": rv["gas_execution"], "gas_total": rv["gas_total"],
        "deployed_bytes": rv["runtime_bytes"], "eip170_ok": rv["eip170_ok"],
        "seconds": round(time.time() - t, 1)}
    log(f"REAL EVM (own compiler + metered VM): gas {rv['gas_total']:,}, "
        f"deployed {rv['runtime_bytes']:,} B "
        f"[{'ok' if rv['eip170_ok'] else 'exceeds-eip170'}]")
    save_record()
    log(f"DONE: record at {record_path}")
    print(json.dumps(record, indent=1))
    return record

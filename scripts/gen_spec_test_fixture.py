#!/usr/bin/env python
"""Generate the vendored consensus-spec-test fixtures (official pyspec file
format) for the Minimal preset — one directory per official case shape
(happy path, multi-update, force-update cut, no-finality, skipped-period
force-update opener). Deterministic; rerun to rebuild.

Run: python scripts/gen_spec_test_fixture.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from spectre_tpu.preprocessor.spec_tests import (SPEC_TEST_SCENARIOS,
                                                 generate_spec_test)
from spectre_tpu.spec import MINIMAL

ROOT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "consensus-spec-tests", "tests", "minimal", "capella",
                    "light_client", "sync", "pyspec_tests")

# scenario -> fixture dir name (official tests use descriptive snake_case
# names; the _selfgen suffix marks vendored self-generated fixtures so real
# downloaded vectors drop in alongside unchanged)
DIRS = {
    "sync": "light_client_sync_selfgen",
    "multi_update": "multi_update_selfgen",
    "force_update_cut": "force_update_cut_selfgen",
    "no_finality": "process_update_no_finality_selfgen",
    "force_update_only": "skipped_period_force_update_selfgen",
}

if __name__ == "__main__":
    assert set(DIRS) == set(SPEC_TEST_SCENARIOS)
    for scenario, name in DIRS.items():
        out = os.path.join(ROOT, name)
        generate_spec_test(out, MINIMAL, scenario=scenario)
        print("wrote", out)
        for f in sorted(os.listdir(out)):
            print(" ", f, os.path.getsize(os.path.join(out, f)), "bytes")

#!/usr/bin/env python
"""Generate the vendored consensus-spec-test fixture (official pyspec file
format) for the Minimal preset. Deterministic; rerun to rebuild.

Run: python scripts/gen_spec_test_fixture.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from spectre_tpu.preprocessor.spec_tests import generate_spec_test
from spectre_tpu.spec import MINIMAL

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "consensus-spec-tests", "tests", "minimal", "capella",
                   "light_client", "sync", "pyspec_tests",
                   "light_client_sync_selfgen")

if __name__ == "__main__":
    generate_spec_test(OUT, MINIMAL)
    print("wrote", OUT)
    for f in sorted(os.listdir(OUT)):
        print(" ", f, os.path.getsize(os.path.join(OUT, f)), "bytes")

#!/usr/bin/env python
"""REAL prove of the sync-step circuit at any spec preset.

Usage: JAX_PLATFORMS=cpu SPECTRE_TRACE=1 python scripts/prove_step.py [spec] [k] [--mock]
Defaults: spec=minimal k=18. `--mock` stops after mock satisfaction.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from spectre_tpu import spec as S
from spectre_tpu.test_utils import default_sync_step_args
from spectre_tpu.models.step import StepCircuit
from spectre_tpu.plonk.srs import SRS


def main():
    args_v = [a for a in sys.argv[1:] if not a.startswith("--")]
    spec = S.SPECS[args_v[0] if args_v else "minimal"]
    k = int(args_v[1]) if len(args_v) > 1 else 18
    mock_only = "--mock" in sys.argv
    t0 = time.time()
    args = default_sync_step_args(spec)
    print(f"[{time.time()-t0:7.1f}s] fixture ready "
          f"({spec.sync_committee_size} pubkeys, signed)", flush=True)
    if mock_only:
        ok = StepCircuit.mock(args, spec, k=k)
        print(f"[{time.time()-t0:7.1f}s] MOCK: {ok}", flush=True)
        assert ok
        return
    srs = SRS.load_or_setup(k)
    print(f"[{time.time()-t0:7.1f}s] srs k={k}", flush=True)
    pk = StepCircuit.create_pk(srs, spec, k, args)
    print(f"[{time.time()-t0:7.1f}s] pk ready", flush=True)
    t1 = time.time()
    proof = StepCircuit.prove(pk, srs, args, spec)
    print(f"[{time.time()-t0:7.1f}s] PROOF DONE: {len(proof)} bytes "
          f"(prove phase {time.time()-t1:.1f}s)", flush=True)
    inst = StepCircuit.get_instances(args, spec)
    ok = StepCircuit.verify(pk.vk, srs, inst, proof)
    print(f"[{time.time()-t0:7.1f}s] verify: {ok}", flush=True)
    assert ok


if __name__ == "__main__":
    main()

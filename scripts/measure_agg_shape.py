#!/usr/bin/env python
"""Measure aggregation-circuit pinnings across outer degrees (VERDICT r4
item 8): the reference compresses with K=23 / 1 advice / lookup 19
(`config/sync_step_verifier_23.json`); this repo's r4 flagship used
k_agg=21 / 12 advice. Fewer columns = fewer witness commitments = smaller
outer proof and cheaper calldata/verifier; fewer rows = faster prove. This
script builds the aggregation context over the CURRENT flagship inner proof
and records the column counts + estimated proof bytes for each k, so the
trade is adopted or rejected with numbers.

Run after the step pipeline's stage 1:
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python scripts/measure_agg_shape.py [--spec testnet] [--k 21]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def estimate_proof_bytes(cfg) -> int:
    """Outer proof size from the config alone: one G1 (64 B uncompressed in
    our wire format: 2x32) per commitment, 32 B per evaluation, plus the two
    SHPLONK witness points. Commitments: advice + per-lookup (pA, pT, z) +
    permutation z chunks + 3 quotient chunks. Evals follow the query plan:
    ~1 per advice/fixed/selector/sigma/table column-rotation pair; the
    dominant, config-derivable part is counted, transcript tails ignored."""
    commitments = (cfg.num_advice + 3 * cfg.num_lookup_advice
                   + cfg.num_perm_chunks + 3)
    evals = (cfg.num_advice * 4              # gate rotations 0..3
             + cfg.num_fixed + cfg.num_advice      # fixed + selectors
             + cfg.num_perm_columns                # sigmas
             + 3 * cfg.num_lookup_advice * 2       # pA/pT/tab + z pairs
             + 2 * cfg.num_perm_chunks)
    return 64 * commitments + 32 * evals + 2 * 64


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--spec", default="testnet")
    ap.add_argument("--k", type=int, default=21)
    ap.add_argument("--out", default=None)
    opts = ap.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")
    from spectre_tpu.plonk.backend import setup_compile_cache
    setup_compile_cache()
    from spectre_tpu import spec as S
    from spectre_tpu.models import AggregationArgs, AggregationCircuit
    from spectre_tpu.models.app_circuit import BUILD_DIR
    from spectre_tpu.models.step import StepCircuit
    from spectre_tpu.plonk.srs import SRS
    from spectre_tpu.witness.step import default_sync_step_args

    spec = S.SPECS[opts.spec]
    k = opts.k
    proof_path = os.path.join(BUILD_DIR,
                              f"step_{spec.name}_{k}_poseidon.proof")
    assert os.path.exists(proof_path), \
        f"{proof_path} missing — run the step pipeline's stage 1 first"
    with open(proof_path, "rb") as f:
        proof = f.read()

    srs = SRS.load_or_setup(k)
    args = default_sync_step_args(spec)
    pk = StepCircuit.create_pk(srs, spec, k, args)   # cached pk load
    inst = StepCircuit.get_instances(args, spec)
    agg_cls = AggregationCircuit.variant(StepCircuit.name)
    agg_args = AggregationArgs(inner_vk=pk.vk, srs=srs,
                               inner_instances=[inst], proof=proof)
    t = time.time()
    ctx = agg_cls.build_context(agg_args, spec)
    cells = ctx.stats()["advice_cells"]
    print(f"agg context: {cells:,} advice cells ({time.time()-t:.0f}s build)")

    rows = []
    for k_agg in range(21, 26):
        try:
            cfg = ctx.auto_config(k=k_agg,
                                  lookup_bits=agg_cls.default_lookup_bits)
        except AssertionError as e:
            print(f"k={k_agg}: {e}")
            continue
        est = estimate_proof_bytes(cfg)
        rows.append({
            "k_agg": k_agg, "num_advice": cfg.num_advice,
            "num_lookup_advice": cfg.num_lookup_advice,
            "est_proof_bytes": est,
            # prove cost scales ~ (columns+const) * n*log n for NTT/MSM work
            "relative_ntt_msm_cost": round(
                (cfg.num_advice + 3 * cfg.num_lookup_advice + 8)
                * (1 << k_agg) * k_agg
                / ((12 + 6 + 8) * (1 << 21) * 21), 2),
        })
        print(f"k={k_agg}: advice={cfg.num_advice} "
              f"lookup={cfg.num_lookup_advice} est_proof={est} B "
              f"rel_cost={rows[-1]['relative_ntt_msm_cost']}")

    out_path = opts.out or os.path.join(BUILD_DIR,
                                        f"agg_shape_{spec.name}_{k}.json")
    with open(out_path, "w") as f:
        json.dump({"inner_proof_bytes": len(proof),
                   "agg_advice_cells": cells, "shapes": rows}, f, indent=1)
    print("wrote", out_path)


if __name__ == "__main__":
    main()

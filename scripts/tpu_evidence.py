#!/usr/bin/env python
"""One-shot TPU evidence capture (`make tpu-evidence`).

The axon tunnel has been wedged for rounds 2-5; when it wakes, every minute
counts. This script runs the full hardware-evidence suite unattended and
writes ONE cumulative JSON (build/tpu_evidence.json), ordered cheap ->
expensive so early results land even if the tunnel re-wedges mid-run:

  1. probe      — jax.devices() under a hard deadline (the wedge mode is a
                  hang, not an error)
  2. bench_aos  — bench.py, plain-XLA AoS MSM kernels
  3. bench_mxu  — bench.py with SPECTRE_FIELD_IMPL=mxu (the int8-limb
                  matmul field formulation on the MXU)
  4. bench_soa  — bench.py with BENCH_IMPL=soa (the Pallas SoA kernel;
                  Mosaic lowering only exists on real TPU backends)
  5. byteeq     — committee-update 512 k=18 REAL prove on TpuBackend
                  (device quotient on) vs CpuBackend, byte-equality
                  (scripts/prove_committee_byteeq.py)

Every stage is a subprocess with its own deadline; a hang kills the child,
not the evidence run. Under CPU-JAX everything still executes and is
LABELED as cpu fallback — so this script is testable on a wedged box.

Run: python scripts/tpu_evidence.py [--quick]  (quick: skip stage 5)
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "build", "tpu_evidence.json")
T0 = time.time()


def log(msg):
    print(f"[{time.time()-T0:7.1f}s] {msg}", flush=True)


def save(evidence):
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(evidence, f, indent=1)


def run_stage(evidence, name, argv, env_extra, timeout, parse_json_line=False):
    env = {**os.environ, **env_extra}
    t = time.time()
    try:
        r = subprocess.run(argv, env=env, cwd=REPO, capture_output=True,
                           text=True, timeout=timeout)
        rec = {"rc": r.returncode, "seconds": round(time.time() - t, 1)}
        if parse_json_line:
            for line in reversed((r.stdout or "").splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    try:
                        rec["result"] = json.loads(line)
                        break
                    except json.JSONDecodeError:
                        continue
        if r.returncode != 0:
            rec["stderr_tail"] = (r.stderr or "")[-2000:]
        else:
            rec["stdout_tail"] = (r.stdout or "")[-1500:]
    except subprocess.TimeoutExpired:
        rec = {"rc": "timeout", "seconds": round(time.time() - t, 1)}
    evidence["stages"][name] = rec
    save(evidence)
    log(f"{name}: rc={rec['rc']} in {rec['seconds']}s")
    return rec


PROBE_SRC = (
    "import json,sys\n"
    "import jax\n"
    "ds=jax.devices()\n"
    "print(json.dumps({'platform': jax.default_backend(),"
    " 'devices': [str(d) for d in ds]}))\n"
)


def main():
    quick = "--quick" in sys.argv
    evidence = {
        "started_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "stages": {},
    }
    save(evidence)

    # -- 1. probe (ambient platform: this is the one place we WANT axon) --
    probe = run_stage(evidence, "probe",
                      [sys.executable, "-c", PROBE_SRC],
                      {}, timeout=150, parse_json_line=True)
    on_device = (probe.get("rc") == 0
                 and probe.get("result", {}).get("platform")
                 not in (None, "cpu"))
    evidence["device_reachable"] = on_device
    save(evidence)
    log(f"device_reachable={on_device} "
        f"({probe.get('result', {}).get('platform')})")

    # -- 2..4. bench variants (bench.py handles its own fallback labeling) --
    bench = [sys.executable, os.path.join(REPO, "bench.py")]
    run_stage(evidence, "bench_aos", bench,
              {"BENCH_IMPL": "aos"}, timeout=2400, parse_json_line=True)
    run_stage(evidence, "bench_mxu", bench,
              {"BENCH_IMPL": "aos", "SPECTRE_FIELD_IMPL": "mxu"},
              timeout=2400, parse_json_line=True)
    if on_device:
        # Mosaic lowering exists only on real TPU backends; on CPU this
        # stage would only re-measure the aos fallback
        run_stage(evidence, "bench_soa", bench,
                  {"BENCH_IMPL": "soa"}, timeout=2400, parse_json_line=True)
    else:
        evidence["stages"]["bench_soa"] = {
            "rc": "skipped", "reason": "pallas/Mosaic needs a real TPU "
            "backend; device unreachable"}
        save(evidence)

    # -- 5. real prove on TpuBackend + byte-equality vs CpuBackend --
    # Two phases with SEPARATE deadlines (r5 lesson: the tunnel wedges
    # long-lived connections mid-bulk-transfer; a keygen routed through the
    # ambient platform hung in tcp_recvmsg while fresh connections worked).
    # Phase cpu pins JAX to CPU (keygen + CpuBackend prove, pk cached);
    # phase tpu is a fresh process on the ambient platform, bounded tighter
    # so a wedge costs 90 min, not 4 h.
    if quick:
        evidence["stages"]["byteeq_cpu"] = {"rc": "skipped",
                                            "reason": "--quick"}
        evidence["stages"]["byteeq_tpu"] = {"rc": "skipped",
                                            "reason": "--quick"}
    else:
        byteeq = os.path.join(REPO, "scripts", "prove_committee_byteeq.py")
        cpu = run_stage(evidence, "byteeq_cpu",
                        [sys.executable, byteeq, "testnet", "18",
                         "--phase=cpu"],
                        {"SPECTRE_TRACE": "1", "JAX_PLATFORMS": "cpu"},
                        timeout=3 * 3600)
        if not on_device:
            evidence["stages"]["byteeq_tpu"] = {
                "rc": "skipped", "reason": "device unreachable"}
            save(evidence)
        elif cpu.get("rc") != 0:
            evidence["stages"]["byteeq_tpu"] = {
                "rc": "skipped", "reason": "cpu phase failed"}
            save(evidence)
        else:
            # run_stage merges os.environ, so the ambient platform (axon)
            # already propagates; the script itself guards against a
            # silent CPU resolution
            run_stage(evidence, "byteeq_tpu",
                      [sys.executable, byteeq, "testnet", "18",
                       "--phase=tpu"],
                      {"SPECTRE_TRACE": "1"}, timeout=90 * 60)

    evidence["finished_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                             time.gmtime())
    save(evidence)
    log(f"evidence written to {OUT}")
    print(json.dumps(
        {k: v.get("rc") for k, v in evidence["stages"].items()}))


if __name__ == "__main__":
    main()

#!/bin/bash
# Tunnel watcher: probe the axon tunnel every ~8 min; the moment it wakes,
# capture the remaining hardware evidence automatically (r5 lesson: awake
# windows last ~40 min — a human polling loop misses them).
#   1. production-size kernel sweep (fresh connection per child — wedge-proof)
#   2. committee-512 k=18 TpuBackend prove vs the cached CPU oracle
# Writes progress to build/tunnel_watch.log (caller redirects); exits after
# one full capture, or keeps probing until killed.
set -u
cd "$(dirname "$0")/.."
PY=/opt/venv/bin/python
export PATH=/opt/venv/bin:$PATH

probe() {
  timeout 120 $PY -c "
import jax
assert jax.default_backend() != 'cpu'
print('awake:', [str(d) for d in jax.devices()])
" 2>/dev/null
}

while true; do
  if probe; then
    echo "[$(date -u +%H:%M:%S)] tunnel AWAKE — capturing evidence"
    ok=1
    echo "[$(date -u +%H:%M:%S)] sweep starting"
    timeout 3600 $PY scripts/tpu_sweep.py || { echo "sweep rc=$?"; ok=0; }
    echo "[$(date -u +%H:%M:%S)] sweep done; byteeq tpu phase starting"
    SPECTRE_TRACE=1 timeout 5400 $PY scripts/prove_committee_byteeq.py \
      testnet 18 --phase tpu || { echo "byteeq tpu rc=$?"; ok=0; }
    if [ "$ok" = 1 ]; then
      echo "[$(date -u +%H:%M:%S)] capture complete"
      exit 0
    fi
    # a stage failed (tunnel re-wedged mid-capture) — back to probing; the
    # sweep saves incrementally and the byteeq oracle is already on disk,
    # so the next awake window resumes cheaply
    echo "[$(date -u +%H:%M:%S)] capture incomplete — resuming probe loop"
  fi
  echo "[$(date -u +%H:%M:%S)] tunnel down"
  sleep 480
done

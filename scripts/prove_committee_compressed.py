#!/usr/bin/env python
"""The reference's SECOND production method end-to-end: committee-update at
reference scale through the COMPRESSED two-stage flow.

Reference parity: `genEvmProof_CommitteeUpdateCompressed`
(`prover/src/rpc.rs:46,55-113`) with K=24-class outer pinning
(`config/committee_update_verifier_24.json`, `justfile:19-21`). The wide-SHA
stage-1 proof carries ~114 region commitments, so the in-circuit verifier is
materially bigger than the step flow's — the reference pays the same cost
with its large outer K; it is recorded honestly here rather than redesigned
away (VERDICT r4 item 2 options).

Run:
    JAX_PLATFORMS=cpu SPECTRE_TRACE=1 \
        python scripts/prove_committee_compressed.py \
        [--spec testnet] [--k 18] [--k-agg auto] [--max-agg-cells 120e6] \
        [--max-agg-advice 16]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SPECTRE_TRACE", "1")

from _compressed_flow import run_compressed_flow  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--spec", default="testnet")
    ap.add_argument("--k", type=int, default=18)
    ap.add_argument("--k-agg", default="auto")
    ap.add_argument("--max-agg-cells", type=float, default=120e6)
    ap.add_argument("--max-agg-advice", type=int, default=16)
    ap.add_argument("--stop-after", choices=["inner", "agg-build", "all"],
                    default="all")
    opts = ap.parse_args()

    from spectre_tpu import spec as S
    from spectre_tpu.models import CommitteeUpdateCircuit
    from spectre_tpu.witness.rotation import default_committee_update_args

    spec = S.SPECS[opts.spec]
    k = opts.k
    run_compressed_flow(
        CommitteeUpdateCircuit, default_committee_update_args,
        spec=spec, k=k, k_agg=opts.k_agg,
        # the reference accepts a LARGE outer K for this flow (K=24); cap
        # columns rather than rows
        k_agg_range=(21, 26),
        max_agg_cells=opts.max_agg_cells,
        max_agg_advice=opts.max_agg_advice,
        record_name=f"compressed_committee_{spec.name}_{k}.json",
        inner_proof_name=f"committee_{spec.name}_{k}_poseidon.proof",
        outer_proof_name=f"agg_committee_{spec.name}_{{k_agg}}_keccak.proof",
        verifier_name=(f"aggregation_committee_{spec.name}"
                       "_{k_agg}_verifier.sol"),
        contract_name="Verifier_aggregation_committee",
        stop_after=opts.stop_after,
        tamper_byte=41)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""End-to-end REAL prove of the sync-step circuit at Minimal (32 validators).

Round-1 VERDICT item 7 / round-3 plan: demonstrate the flagship circuit at a
reference spec preset (not just the tiny demo net), full in-circuit BLS block
included. Run: JAX_PLATFORMS=cpu SPECTRE_TRACE=1 python scripts/prove_minimal_step.py [k]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from spectre_tpu.spec import MINIMAL
from spectre_tpu.test_utils import default_sync_step_args
from spectre_tpu.models.step import StepCircuit
from spectre_tpu.plonk.srs import SRS


def main():
    k = int(sys.argv[1]) if len(sys.argv) > 1 else 18
    t0 = time.time()
    args = default_sync_step_args(MINIMAL)
    print(f"[{time.time()-t0:7.1f}s] fixture ready (32 pubkeys, signed)",
          flush=True)
    srs = SRS.load_or_setup(k)
    print(f"[{time.time()-t0:7.1f}s] srs k={k}", flush=True)
    pk = StepCircuit.create_pk(srs, MINIMAL, k, args)
    print(f"[{time.time()-t0:7.1f}s] pk ready", flush=True)
    t1 = time.time()
    proof = StepCircuit.prove(pk, srs, args, MINIMAL)
    print(f"[{time.time()-t0:7.1f}s] PROOF DONE: {len(proof)} bytes "
          f"(prove phase {time.time()-t1:.1f}s)", flush=True)
    inst = StepCircuit.get_instances(args, MINIMAL)
    ok = StepCircuit.verify(pk.vk, srs, inst, proof)
    print(f"[{time.time()-t0:7.1f}s] verify: {ok}", flush=True)
    assert ok


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""The production artifact end-to-end: sync-step at reference scale through
the COMPRESSED two-stage flow.

    stage 1: StepCircuit prove (Poseidon transcript) at --spec/--k
    stage 2: AggregationCircuit outer prove (Keccak transcript) at auto-k
    finish:  encode_calldata + generated Solidity verifier accepts the proof

Reference parity: the `genEvmProof_SyncStepCompressed` path
(`prover/src/rpc.rs:114-163`) and the full two-stage test
(`sync_step_circuit.rs:544-604`).

Checkpoints land in build/ so a crashed run resumes: the inner proof and the
outer proof are only regenerated when absent. Run:

    JAX_PLATFORMS=cpu SPECTRE_TRACE=1 python scripts/prove_step_compressed.py \
        [--spec testnet] [--k 21] [--k-agg auto] [--max-agg-cells 90e6]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SPECTRE_TRACE", "1")

T0 = time.time()


def log(msg):
    print(f"[{time.time()-T0:8.1f}s] {msg}", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--spec", default="testnet")
    ap.add_argument("--k", type=int, default=21)
    ap.add_argument("--k-agg", default="auto")
    ap.add_argument("--max-agg-cells", type=float, default=90e6)
    ap.add_argument("--stop-after", choices=["inner", "agg-build", "all"],
                    default="all")
    opts = ap.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")
    from spectre_tpu.plonk.backend import setup_compile_cache
    setup_compile_cache()

    from spectre_tpu import spec as S
    from spectre_tpu.models import AggregationArgs, AggregationCircuit
    from spectre_tpu.models.app_circuit import BUILD_DIR
    from spectre_tpu.models.step import StepCircuit
    from spectre_tpu.plonk.srs import SRS
    from spectre_tpu.plonk.transcript import (KeccakTranscript,
                                              PoseidonTranscript)
    from spectre_tpu.witness.step import default_sync_step_args

    spec = S.SPECS[opts.spec]
    k = opts.k
    record_path = os.path.join(BUILD_DIR, f"compressed_{spec.name}_{k}.json")
    record = {"spec": spec.name, "k_step": k}
    if os.path.exists(record_path):
        with open(record_path) as f:
            record.update(json.load(f))

    def save_record():
        with open(record_path, "w") as f:
            json.dump(record, f, indent=1)

    # ---- fixture (the deterministic reference-scale witness) ----
    args = default_sync_step_args(spec)
    log(f"fixture ready ({spec.sync_committee_size} pubkeys, signed)")

    # ---- stage 1: inner snark (Poseidon transcript) ----
    srs = SRS.load_or_setup(k)
    log(f"srs k={k}")
    t = time.time()
    pk = StepCircuit.create_pk(srs, spec, k, args)
    record.setdefault("keygen_s", round(time.time() - t, 1))
    cfg = pk.vk.config
    log(f"step pk ready: advice={cfg.num_advice} lookup={cfg.num_lookup_advice} "
        f"tables={cfg.lookup_tables} fixed={cfg.num_fixed}")
    record["step_config"] = {
        "num_advice": cfg.num_advice,
        "num_lookup_advice": cfg.num_lookup_advice,
        "lookup_bits": cfg.lookup_bits, "num_sha_slots": cfg.num_sha_slots}
    save_record()

    proof_path = os.path.join(BUILD_DIR, f"step_{spec.name}_{k}_poseidon.proof")
    inst = StepCircuit.get_instances(args, spec)
    if os.path.exists(proof_path):
        with open(proof_path, "rb") as f:
            proof = f.read()
        log(f"stage-1 proof loaded from cache ({len(proof)} bytes)")
    else:
        t = time.time()
        proof = StepCircuit.prove(pk, srs, args, spec,
                                  transcript=PoseidonTranscript())
        record["stage1_prove_s"] = round(time.time() - t, 1)
        with open(proof_path, "wb") as f:
            f.write(proof)
        log(f"STAGE-1 PROOF: {len(proof)} bytes in {record['stage1_prove_s']}s")
    record["stage1_proof_bytes"] = len(proof)
    t = time.time()
    # verify with the same transcript the proof was produced with
    from spectre_tpu.plonk.verifier import verify as plonk_verify
    ok = plonk_verify(pk.vk, srs, [inst], proof,
                      transcript_cls=PoseidonTranscript)
    assert ok, "stage-1 proof does not verify"
    record["stage1_verify_s"] = round(time.time() - t, 1)
    log(f"stage-1 verifies ({record['stage1_verify_s']}s)")
    save_record()
    if opts.stop_after == "inner":
        return

    # ---- stage 2: aggregation ----
    agg_cls = AggregationCircuit.variant(StepCircuit.name)
    agg_args = AggregationArgs(inner_vk=pk.vk, srs=srs,
                               inner_instances=[inst], proof=proof)
    t = time.time()
    ctx = agg_cls.build_context(agg_args, spec)
    st = ctx.stats()
    record["agg_build_s"] = round(time.time() - t, 1)
    record["agg_advice_cells"] = st["advice_cells"]
    record["agg_lookup_cells"] = sum(st["lookup_cells"].values())
    log(f"agg circuit built in {record['agg_build_s']}s: "
        f"{st['advice_cells']:,} advice cells, "
        f"{record['agg_lookup_cells']:,} lookup cells")
    save_record()
    assert st["advice_cells"] <= opts.max_agg_cells, \
        f"aggregation circuit too large ({st['advice_cells']:,} cells)"

    if opts.k_agg == "auto":
        # smallest k whose column count stays in the reference's envelope
        # (their verifier pins K=23 with 1 advice column at lookup 19)
        cagg = None
        for k_agg in range(20, 25):
            cagg = ctx.auto_config(k=k_agg,
                                   lookup_bits=agg_cls.default_lookup_bits)
            if cagg.num_advice <= 12:
                break
        assert cagg is not None and cagg.num_advice <= 12, \
            f"no k in 20..24 reaches <=12 advice (k=24: {cagg.num_advice})"
    else:
        k_agg = int(opts.k_agg)
        cagg = ctx.auto_config(k=k_agg,
                               lookup_bits=agg_cls.default_lookup_bits)
    record["k_agg"] = k_agg
    record["agg_config"] = {"num_advice": cagg.num_advice,
                            "num_lookup_advice": cagg.num_lookup_advice}
    log(f"agg k={k_agg}: advice={cagg.num_advice} "
        f"lookup={cagg.num_lookup_advice}")
    save_record()
    if opts.stop_after == "agg-build":
        return

    srs_agg = SRS.load_or_setup(k_agg)
    log(f"srs k={k_agg}")
    t = time.time()
    agg_pk = agg_cls.create_pk(srs_agg, spec, k_agg, agg_args)
    record.setdefault("agg_keygen_s", round(time.time() - t, 1))
    log("agg pk ready")
    save_record()

    oproof_path = os.path.join(
        BUILD_DIR, f"agg_step_{spec.name}_{k_agg}_keccak.proof")
    if os.path.exists(oproof_path):
        with open(oproof_path, "rb") as f:
            oproof = f.read()
        with open(oproof_path + ".instances.json") as f:
            stmt = [int(v, 16) for v in json.load(f)["instances"]]
        log(f"stage-2 proof loaded from cache ({len(oproof)} bytes)")
    else:
        stmt = AggregationCircuit.get_instances(agg_args, spec)
        t = time.time()
        oproof = agg_cls.prove(agg_pk, srs_agg, agg_args, spec,
                               transcript=KeccakTranscript())
        record["stage2_prove_s"] = round(time.time() - t, 1)
        with open(oproof_path, "wb") as f:
            f.write(oproof)
        with open(oproof_path + ".instances.json", "w") as f:
            json.dump({"instances": [hex(v) for v in stmt]}, f)
        log(f"STAGE-2 PROOF: {len(oproof)} bytes in {record['stage2_prove_s']}s")
    record["stage2_proof_bytes"] = len(oproof)
    t = time.time()
    ok = agg_cls.verify(agg_pk.vk, srs_agg, stmt, oproof,
                        transcript_cls=KeccakTranscript)
    assert ok, "outer proof (incl. deferred pairing) does not verify"
    record["stage2_verify_s"] = round(time.time() - t, 1)
    log(f"stage-2 verifies incl. deferred KZG pairing "
        f"({record['stage2_verify_s']}s)")
    save_record()

    # ---- EVM artifact: calldata + generated verifier executes ----
    from spectre_tpu.evm import encode_calldata, gen_evm_verifier
    from spectre_tpu.evm.simulator import run_verifier
    calldata = encode_calldata(stmt, oproof)
    record["calldata_bytes"] = len(calldata)
    t = time.time()
    sol = gen_evm_verifier(agg_pk.vk, srs_agg, num_instances=len(stmt),
                           contract_name="Verifier_aggregation_sync_step",
                           num_acc_limbs=12)
    sol_path = os.path.join(
        BUILD_DIR, f"aggregation_sync_step_{spec.name}_{k_agg}_verifier.sol")
    with open(sol_path, "w") as f:
        f.write(sol)
    record["verifier_sol_bytes"] = len(sol)
    log(f"EVM verifier generated: {len(sol)} bytes source")
    ok = run_verifier(sol, stmt, oproof)
    assert ok, "generated Solidity verifier rejected the outer proof"
    bad = bytearray(oproof)
    bad[37] ^= 1
    assert not run_verifier(sol, stmt, bytes(bad)), \
        "generated verifier accepted a tampered proof"
    record["evm_verifier_s"] = round(time.time() - t, 1)
    record["evm_verifier_ok"] = True
    # static gas + deployed-size model (reference prints these from revm,
    # `prover/src/cli.rs:249-277`; offline equivalent — evm/gas.py)
    from spectre_tpu.evm import estimate_deployed_size, estimate_gas
    g = estimate_gas(sol, calldata=calldata)
    sz = estimate_deployed_size(sol)
    record["gas_estimate"] = {k: v for k, v in g.items() if k != "counts"}
    record["deployed_size_estimate"] = sz
    log(f"gas estimate: {g.get('gas_total', g['gas_execution']):,} "
        f"(execution {g['gas_execution']:,}); deployed size ~"
        f"{sz['deployed_bytes_estimate']:,} B [{sz['deployed_size_risk']}]")
    save_record()
    log(f"DONE: record at {record_path}")
    print(json.dumps(record, indent=1))


if __name__ == "__main__":
    main()

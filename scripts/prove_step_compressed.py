#!/usr/bin/env python
"""The production artifact end-to-end: sync-step at reference scale through
the COMPRESSED two-stage flow.

    stage 1: StepCircuit prove (Poseidon transcript) at --spec/--k
    stage 2: AggregationCircuit outer prove (Keccak transcript) at auto-k
    finish:  encode_calldata + generated Solidity verifier accepts the proof
             + static gas / deployed-size estimates

Reference parity: the `genEvmProof_SyncStepCompressed` path
(`prover/src/rpc.rs:114-163`) and the full two-stage test
(`sync_step_circuit.rs:544-604`).

Checkpoints land in build/ so a crashed run resumes: the inner proof and the
outer proof are only regenerated when absent. Run:

    JAX_PLATFORMS=cpu SPECTRE_TRACE=1 python scripts/prove_step_compressed.py \
        [--spec testnet] [--k 21] [--k-agg auto] [--max-agg-cells 90e6]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SPECTRE_TRACE", "1")

from _compressed_flow import run_compressed_flow  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--spec", default="testnet")
    ap.add_argument("--k", type=int, default=21)
    ap.add_argument("--k-agg", default="auto")
    ap.add_argument("--max-agg-cells", type=float, default=90e6)
    ap.add_argument("--max-agg-advice", type=int, default=12)
    ap.add_argument("--stop-after", choices=["inner", "agg-build", "all"],
                    default="all")
    opts = ap.parse_args()

    from spectre_tpu import spec as S
    from spectre_tpu.models.step import StepCircuit
    from spectre_tpu.witness.step import default_sync_step_args

    spec = S.SPECS[opts.spec]
    k = opts.k
    run_compressed_flow(
        StepCircuit, default_sync_step_args,
        spec=spec, k=k, k_agg=opts.k_agg,
        # smallest outer k whose column count stays in the reference's
        # envelope (their verifier pins K=23 with 1 advice at lookup 19)
        k_agg_range=(20, 25),
        max_agg_cells=opts.max_agg_cells,
        max_agg_advice=opts.max_agg_advice,
        record_name=f"compressed_{spec.name}_{k}.json",
        inner_proof_name=f"step_{spec.name}_{k}_poseidon.proof",
        outer_proof_name=f"agg_step_{spec.name}_{{k_agg}}_keccak.proof",
        verifier_name=(f"aggregation_sync_step_{spec.name}"
                       "_{k_agg}_verifier.sol"),
        contract_name="Verifier_aggregation_sync_step",
        stop_after=opts.stop_after,
        tamper_byte=37)


if __name__ == "__main__":
    main()
